"""TPU generation server — the SGLang/JetStream role, in-house.

Parity target: ``realhf/system/generation_server.py`` + the sglang patch
(``patch/sglang/v0.4.6.post4.patch``: interruptible generation, weight
update from disk). TPU-first design differences:

 - **Chunked decoding replaces interruption.** The reference patches SGLang
   to abort in-flight requests when weights update. Here every ``/generate``
   call decodes AT MOST ``chunk_tokens`` new tokens as one static-shape
   ``lax.scan`` and returns a partial result tagged with the weight version
   that produced it; the client (PartialRolloutManager) re-submits with the
   accumulated prefix. Weight updates therefore wait at most one chunk —
   the same bound the reference achieves by aborting, with zero lost work
   and no recompilation (chunk length is static).
 - **Micro-batched continuous batching**: concurrent requests are drained
   from a queue every ``batch_window_ms`` and decoded together, padded to
   bucketed prompt lengths (prefix re-prefill per chunk; a paged KV cache
   across chunks is a later optimization).
 - ``/update_weights`` hot-swaps params in place (device_put over the old
   sharding) from the trainer's publish — either streamed per-tensor over
   ZMQ (§3.5 low-latency path, system/weight_stream.py) or read from the
   published checkpoint (disk fallback).

Endpoints: POST /generate, POST /update_weights, GET /health,
GET /metrics (Prometheus text), GET /metrics.json (structured).
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.api.train_config import TelemetryConfig
from areal_tpu.base import logging, name_resolve, names, network, telemetry
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer  # noqa: F401 (engine deps)

logger = logging.getLogger("system.genserver")


@dataclasses.dataclass
class GenerationServerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    server_id: str = "gen0"
    chunk_tokens: int = 128  # static decode length per /generate call
    batch_window_ms: int = 5
    max_batch_size: int = 64
    prompt_bucket: int = 128
    eos_token_id: int = 1
    pad_token_id: int = 0
    port: Optional[int] = None
    # Persistent-KV continuous batching: keep per-request decode state so a
    # chunk continuation decodes from its cache instead of re-prefilling the
    # whole prefix (the reference's SGLang radix-cache role). 0 disables.
    kv_slots: int = 256
    kv_bucket: int = 256  # KV capacity granularity (slots)
    # Hard budget on retained KV BYTES (not just state count): per-request
    # KV grows with sequence length, so count alone can exhaust HBM long
    # before kv_slots states (advisor r2, medium). LRU-evicted states simply
    # re-prefill on their next chunk.
    kv_bytes_budget: int = 4 << 30
    # In-flight chunk requests when consuming a streamed weight update
    # (weight_sync.pipeline_depth threaded through the experiment config).
    weight_stream_pipeline_depth: int = 4
    # Unified telemetry (base/telemetry.py). The gen-fleet process hosts
    # servers AND the manager, so each owns its own instance (distinct
    # worker kinds at the aggregator) instead of the process global.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )


class _Pending:
    __slots__ = ("rid", "prompt", "gconfig", "future", "max_tokens",
                 "tokens_done")

    def __init__(self, prompt, gconfig, max_tokens, future, rid=None,
                 tokens_done=0):
        self.rid = rid
        self.prompt = prompt
        self.gconfig = gconfig
        self.max_tokens = max_tokens
        self.tokens_done = tokens_done
        self.future = future


class _ReqState:
    """Server-resident decode state of one in-flight chunked request."""

    __slots__ = ("state", "cur_len", "version", "last_used", "nbytes")

    def __init__(self, state, cur_len: int, version: int):
        self.state = state  # single-row decode state (models.generate)
        self.cur_len = cur_len
        self.version = version
        self.last_used = time.monotonic()
        self.nbytes = state["kv_k"].nbytes + state["kv_v"].nbytes


class GenerationServer:
    """Owns (cfg, params) of the serving model; hot-swappable."""

    def __init__(self, cfg: GenerationServerConfig, model_cfg, params,
                 mesh=None):
        self.cfg = cfg
        self.model_cfg = model_cfg
        import jax

        if mesh is not None:
            from areal_tpu.parallel import sharding as psh

            params = psh.shard_params(params, mesh, model_cfg)
        else:
            params = jax.tree.map(jax.numpy.asarray, params)
        self.params = params
        self.mesh = mesh
        self.version = 0
        self._queue: asyncio.Queue = None  # created on loop start
        self._key = jax.random.PRNGKey(0)
        self._tokens_out = 0
        self._prefill_tokens = 0
        self._t_start = time.monotonic()
        self._runner_task = None
        self._states: Dict[str, _ReqState] = {}
        self._last_update_latency = 0.0
        self._inflight = 0  # /generate requests accepted but not replied
        self._last_stream_stats: Dict[str, float] = {}
        # server_id "gen3" → worker_index 3 at the aggregator.
        idx = "".join(c for c in cfg.server_id if c.isdigit())
        self.telemetry = (
            telemetry.Telemetry(
                cfg.experiment, cfg.trial, "generation_server",
                int(idx or 0), cfg=cfg.telemetry,
            ) if cfg.telemetry.enabled else telemetry.NULL
        )

    # ---------------- decode core ----------------

    def _decode_batch(self, batch: List[_Pending]) -> List[Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        # Capture (params, version) atomically: handle_update_weights swaps
        # both on the event loop while we run in a thread, and tokens
        # sampled under the old weights must be tagged with the version
        # that actually produced them (decoupled-loss bookkeeping).
        params, version = self.params, self.version
        # Sampling params are per-ROW dynamic arrays (ops.sampling), so a
        # batch may freely mix gconfigs; only the chunk length (static) is
        # shared, and decode recompiles only per distinct final-chunk size.
        chunk = min(cfg.chunk_tokens, max(p.max_tokens for p in batch))

        # Split: requests whose decode state survived (same version, prefix
        # length matches) continue from their KV; the rest prefill.
        cont: List[_Pending] = []
        fresh: List[_Pending] = []
        for p in batch:
            st = None
            if p.rid is not None and cfg.kv_slots > 0:
                st = self._states.get(p.rid)
            if (
                st is not None and st.version == version
                and st.cur_len == len(p.prompt)
            ):
                st.last_used = time.monotonic()
                cont.append(p)
            else:
                fresh.append(p)

        row_states = {}
        if fresh:
            padded, plens = genmod.pad_prompts(
                [p.prompt for p in fresh], cfg.pad_token_id,
                bucket=cfg.prompt_bucket,
            )
            S = self._round_capacity(padded.shape[1] + chunk)
            st = genmod.prefill_state(
                params, self.model_cfg, jnp.asarray(padded),
                jnp.asarray(plens), S,
            )
            self._prefill_tokens += int(plens.sum())
            for i, p in enumerate(fresh):
                row_states[id(p)] = genmod.slice_state(st, i)
        for p in cont:
            rs = self._states[p.rid]
            row_states[id(p)] = genmod.grow_state(
                rs.state, self._round_capacity(rs.cur_len + chunk)
            )

        # Group rows by KV capacity (static shape per decode_chunk call).
        groups: Dict[int, List[_Pending]] = {}
        for p in batch:
            S = row_states[id(p)]["kv_k"].shape[2]
            groups.setdefault(S, []).append(p)

        res_by_id: Dict[int, Dict[str, Any]] = {}
        for S, group in groups.items():
            stacked = genmod.stack_states([row_states[id(p)] for p in group])
            done = jnp.asarray([p.tokens_done for p in group], jnp.int32)
            self._key, sub = jax.random.split(self._key)
            from areal_tpu.ops.sampling import sampling_from_gconfigs

            new_state, out = genmod.decode_chunk_rows(
                params, self.model_cfg, stacked, done, sub,
                sampling_from_gconfigs([p.gconfig for p in group]),
                n_tokens=chunk,
                eos_token_id=cfg.eos_token_id, pad_token_id=cfg.pad_token_id,
                # Rows with a smaller remaining budget than the batch chunk
                # stop sampling at their own allowance.
                row_budget=jnp.asarray(
                    [min(p.max_tokens, chunk) for p in group], jnp.int32
                ),
            )
            out = jax.device_get(out)
            for i, p in enumerate(group):
                # Never hand back more than the request's remaining budget —
                # the client appends every token we return.
                n = min(int(out["output_lens"][i]), p.max_tokens)
                toks = np.asarray(out["output_ids"][i][:n])
                lps = np.asarray(out["output_logprobs"][i][:n])
                # "finished" = the MODEL ended the sequence (EOS). Budget
                # exhaustion is the client's call — it knows the total
                # budget across chunks, we only see this chunk's slice.
                emitted_eos = bool((toks == cfg.eos_token_id).any())
                res_by_id[id(p)] = {
                    "output_ids": toks.tolist(),
                    "output_logprobs": lps.tolist(),
                    "finished": emitted_eos,
                    "version": version,
                }
                self._tokens_out += n
                if p.rid is not None and cfg.kv_slots > 0:
                    if emitted_eos or n >= p.max_tokens:
                        self._states.pop(p.rid, None)
                    elif n == chunk:
                        # Keep state only if the client's next prefix will
                        # be exactly prompt+chunk (budget truncation would
                        # desync cur_len; those re-prefill).
                        self._states[p.rid] = _ReqState(
                            genmod.slice_state(new_state, i),
                            cur_len=len(p.prompt) + n,
                            version=version,
                        )
                    else:
                        self._states.pop(p.rid, None)
        self._evict_states()
        return [res_by_id[id(p)] for p in batch]

    def _round_capacity(self, n: int) -> int:
        b = self.cfg.kv_bucket
        return ((n + b - 1) // b) * b

    def _evict_states(self) -> None:
        cap = self.cfg.kv_slots
        if cap <= 0:
            self._states.clear()
            return
        total_bytes = sum(s.nbytes for s in self._states.values())
        while len(self._states) > cap or (
            total_bytes > self.cfg.kv_bytes_budget and self._states
        ):
            oldest = min(self._states, key=lambda r: self._states[r].last_used)
            total_bytes -= self._states[oldest].nbytes
            del self._states[oldest]

    async def _runner(self):
        cfg = self.cfg
        while True:
            first: _Pending = await self._queue.get()
            batch = [first]
            await asyncio.sleep(cfg.batch_window_ms / 1000)
            # Drain in FIFO order up to max_batch_size. Sampling params are
            # per-row vectors inside the decode kernel, so mixed gconfigs
            # batch together — no deferral, no starvation.
            while len(batch) < cfg.max_batch_size and not self._queue.empty():
                batch.append(self._queue.get_nowait())
            try:
                with self.telemetry.span("genserver/decode_chunk",
                                         batch_size=len(batch)) as attrs:
                    results = await asyncio.to_thread(
                        self._decode_batch, batch
                    )
                    attrs["tokens"] = sum(
                        len(r["output_ids"]) for r in results
                    )
                self.telemetry.inc("genserver/decode_chunks")
                self.telemetry.inc("genserver/generated_tokens",
                                   attrs["tokens"])
                for p, r in zip(batch, results):
                    p.future.set_result(r)
            except asyncio.CancelledError:
                # Server stopping mid-decode: fail the batch so its HTTP
                # handlers return immediately instead of hanging through
                # the runner's graceful-shutdown window.
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(
                            RuntimeError("generation server stopping")
                        )
                raise
            except Exception as e:  # noqa: BLE001 — propagate per-request
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(e)

    # ---------------- http ----------------

    async def handle_generate(self, request):
        from aiohttp import web

        d = await request.json()
        gconfig = GenerationHyperparameters(**d.get("gconfig", {}))
        fut = asyncio.get_running_loop().create_future()
        self._inflight += 1
        try:
            await self._queue.put(_Pending(
                prompt=np.asarray(d["prompt_ids"], np.int32),
                gconfig=gconfig,
                max_tokens=int(d.get("max_tokens", gconfig.max_new_tokens)),
                future=fut,
                rid=d.get("rid"),
                tokens_done=int(d.get("tokens_done", 0)),
            ))
            return web.json_response(await fut)
        finally:
            self._inflight -= 1

    def _load_and_put_weights(self, path: str):
        """Host-side checkpoint read + device upload. Runs in a worker
        thread — the event loop (and /generate batching) never blocks on
        disk or transfer; only the final reference swap happens on-loop."""
        import jax

        from areal_tpu.models import hf as hfmod

        _, params = hfmod.load_checkpoint_auto(path)
        # Preserve the existing per-leaf device placement/sharding.
        return jax.tree.map(
            lambda old, npv: jax.device_put(
                np.asarray(npv, dtype=old.dtype), old.sharding
            ),
            self.params,
            params,
        )

    def _stream_and_put_weights(self, endpoint: str, version: int,
                                timeout_secs: Optional[float] = None):
        """Streamed transport (docs/weight_sync.md): pull the manifest +
        per-tensor chunks from the trainer's WeightStreamPublisher into a
        SHADOW pytree, device_put'ing each tensor as it lands so the h2d
        upload of tensor i−1 overlaps the wire transfer of tensor i (whose
        d2h gather the publisher is doing concurrently). The shadow tree
        only replaces ``self.params`` after the publisher's digest verifies
        the complete stream — a torn, reordered, or corrupted transfer
        raises before anything live is touched."""
        import jax

        from areal_tpu.models.hf import flatten_pytree, unflatten_pytree
        from areal_tpu.system.weight_stream import (
            WeightStreamConsumer,
            WeightStreamError,
        )

        old_flat = flatten_pytree(self.params)
        consumer = WeightStreamConsumer(
            endpoint,
            pipeline_depth=self.cfg.weight_stream_pipeline_depth,
            **({} if timeout_secs is None
               else {"timeout_secs": timeout_secs}),
        )
        try:
            manifest = consumer.fetch_manifest(version)
            shadow = {}
            for name, arr in consumer.iter_tensors(version, manifest):
                old = old_flat.get(name)
                if old is None:
                    raise WeightStreamError(
                        f"streamed tensor {name!r} not in the live pytree"
                    )
                if tuple(arr.shape) != tuple(old.shape):
                    raise WeightStreamError(
                        f"tensor {name!r}: streamed shape {arr.shape} != "
                        f"live {old.shape}"
                    )
                # Async dispatch: device_put returns immediately, so the
                # upload runs while the next chunks arrive.
                shadow[name] = jax.device_put(
                    np.asarray(arr, dtype=old.dtype), old.sharding
                )
            if set(shadow) != set(old_flat):
                missing = sorted(set(old_flat) - set(shadow))
                raise WeightStreamError(
                    f"incomplete stream: {len(missing)} tensors missing "
                    f"(e.g. {missing[:3]})"
                )
            # The gate: no swap without a checksum-verified manifest.
            consumer.verify_digest(version)
            new = unflatten_pytree(shadow)
            jax.block_until_ready(new)
            # Per-leg stream stats for /metrics + telemetry: wire wait,
            # digest/checksum CPU, and total bytes of this consume.
            # Recorded ONLY on a verified success — a failed update must
            # leave /metrics unchanged (the except handler's contract).
            self._last_stream_stats = {
                "stream_bytes": float(consumer.bytes_received),
                "digest_verify_secs": consumer.checksum_secs,
                "wire_wait_secs": consumer.wire_wait_secs,
            }
            return new
        finally:
            consumer.close()

    async def handle_update_weights(self, request):
        from aiohttp import web

        d = await request.json()
        t0 = time.monotonic()
        transport = "stream" if d.get("endpoint") else "disk"
        try:
            with self.telemetry.span("genserver/weight_update",
                                     transport=transport,
                                     version=int(d.get("version", -1))):
                if d.get("endpoint"):
                    new = await asyncio.to_thread(
                        self._stream_and_put_weights, d["endpoint"],
                        int(d["version"]),
                        d.get("timeout"),
                    )
                else:
                    new = await asyncio.to_thread(
                        self._load_and_put_weights, d["path"]
                    )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — keep old weights, report
            # Old (params, version) stay live and /metrics unchanged; the
            # manager's fanout retry/eviction machinery owns what happens
            # to this server next (docs/fault_tolerance.md).
            self.telemetry.inc("genserver/weight_update_failures")
            logger.error(f"weight update failed; keeping v{self.version}: {e}")
            return web.json_response(
                {"ok": False, "version": self.version, "error": str(e)},
                status=500,
            )
        # Atomic (params, version) swap: in-flight _decode_batch threads
        # captured the old pair and tag their tokens with the old version.
        self.params = new
        self.version = int(d.get("version", self.version + 1))
        # KV computed under the old weights is stale — continuations after
        # a version change re-prefill once (reference: SGLang flushes its
        # cache on update_weights_from_disk).
        self._states.clear()
        dt = time.monotonic() - t0
        self._last_update_latency = dt
        self.telemetry.set_gauge("genserver/weight_version", self.version)
        self.telemetry.set_gauge("genserver/weight_update_secs", dt)
        if transport == "stream":
            # Disk updates must not republish the previous stream's stats
            # as if they described this sync.
            for k, v in self._last_stream_stats.items():
                self.telemetry.set_gauge(f"genserver/{k}", v)
        logger.info(f"weights updated to v{self.version} in {dt:.2f}s")
        return web.json_response({"ok": True, "version": self.version,
                                  "latency_s": dt})

    async def handle_health(self, request):
        # Polled by the gserver manager's fleet-health loop: ``version`` is
        # what the manager reconciles against when re-admitting this server
        # after an eviction (docs/fault_tolerance.md).
        from aiohttp import web

        return web.json_response({
            "ok": True,
            "version": self.version,
            "server_id": self.cfg.server_id,
            "uptime_secs": time.monotonic() - self._t_start,
        })

    def _metrics_dict(self) -> Dict[str, Any]:
        dt = max(time.monotonic() - self._t_start, 1e-6)
        return {
            "generated_tokens": self._tokens_out,
            "prefill_tokens": self._prefill_tokens,
            "tokens_per_sec": self._tokens_out / dt,
            "kv_states": len(self._states),
            "kv_bytes": sum(s.nbytes for s in self._states.values()),
            "version": self.version,
            "inflight_requests": self._inflight,
            "queue_depth": self._queue.qsize() if self._queue else 0,
            "last_weight_update_latency_s": self._last_update_latency,
            # Stats of the last SUCCESSFUL streamed consume (absent until
            # one lands; a later disk update does not describe these).
            **{f"last_stream_{k}": v
               for k, v in self._last_stream_stats.items()},
        }

    async def handle_metrics(self, request):
        """Prometheus exposition text (docs/observability.md): live server
        state as ``areal_genserver_*`` gauges — including weight_version
        and inflight_requests — plus this server's telemetry registry
        (decode spans → histograms) when telemetry is enabled. The old
        JSON body moved to ``/metrics.json``."""
        from aiohttp import web

        d = self._metrics_dict()
        extra = {f"genserver_{k}": v for k, v in d.items()}
        # Canonical gauge name, present from boot (the registry's copy
        # only exists once the first /update_weights lands).
        extra["genserver_weight_version"] = d["version"]
        body = telemetry.render_prometheus(
            self.telemetry.snapshot(reset=False),
            extra_gauges=extra,
            labels={"server_id": self.cfg.server_id},
        )
        return web.Response(
            text=body, content_type="text/plain",
            charset="utf-8", headers={"X-Prometheus-Version": "0.0.4"},
        )

    async def handle_metrics_json(self, request):
        from aiohttp import web

        return web.json_response(self._metrics_dict())

    def build_app(self):
        from aiohttp import web

        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_post("/generate", self.handle_generate)
        app.router.add_post("/update_weights", self.handle_update_weights)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/metrics.json", self.handle_metrics_json)
        return app

    async def start(self) -> str:
        """Start serving; registers the URL under names.gen_servers."""
        from aiohttp import web

        self._queue = asyncio.Queue()
        self._runner_task = asyncio.create_task(self._runner())
        app = self.build_app()
        runner = web.AppRunner(app)
        await runner.setup()
        port = self.cfg.port or network.find_free_port()
        site = web.TCPSite(runner, network.bind_addr(), port)
        await site.start()
        url = f"http://{network.gethostip()}:{port}"
        name_resolve.add(
            names.gen_servers(self.cfg.experiment, self.cfg.trial,
                              self.cfg.server_id),
            url, replace=True,
        )
        logger.info(f"generation server {self.cfg.server_id} at {url}")
        self._runner_obj = runner
        return url

    async def stop(self, abort: bool = False):
        """Stop serving. ``abort=True`` is the crash-like path (chaos
        tests): queued requests are failed immediately instead of drained,
        so connected clients see errors now rather than a hung socket."""
        if self._runner_task:
            self._runner_task.cancel()
        if abort and self._queue is not None:
            while not self._queue.empty():
                p = self._queue.get_nowait()
                if not p.future.done():
                    p.future.set_exception(RuntimeError("server aborted"))
        self.telemetry.close()
        await self._runner_obj.cleanup()
