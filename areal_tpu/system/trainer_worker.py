"""Trainer worker — hosts model roles on one mesh and executes MFCs.

Parity target: ``realhf/system/model_worker.py:101``. TPU-first collapse:
JAX is single-controller SPMD, so the reference's one-process-per-GPU model
workers (with NCCL data redistribution between them, ``data_manager.py``,
``redistributor.py``) become ONE process driving the whole trainer mesh —
the DataManager shrinks to an in-process dict, and GSPMD handles every
intra-mesh reshard the reference planned centrally.

Serves the master's request stream with handlers:
 - ``fetch``          next dataset batch → store → metadata
 - ``mfc``            run one MFC (generate/inference/train_step) over
                      stored samples; store outputs; reply metadata
 - ``clear``          drop sample ids from the store
 - ``save`` / ``version`` / ``exit``  bookkeeping

Pre/post hooks on MFC payloads: ``weight_update`` publishes actor weights
for the generation fleet (disk path + names.model_version bump — §3.5 of
the survey), ``param_realloc`` does EMA role sync, ``save`` checkpoints.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    FinetuneSpec,
    Model,
    make_backend,
    make_dataset,
    make_interface,
)
from areal_tpu.api.train_config import (
    CompileWatchConfig,
    DurabilityConfig,
    GoodputConfig,
    RewardServiceConfig,
    TelemetryConfig,
    WeightSyncConfig,
)
from areal_tpu.base import compile_watch, logging, name_resolve, names, \
    telemetry
from areal_tpu.system import goodput as goodput_mod
from areal_tpu.system import memwatch
from areal_tpu.system.sample_spool import (
    SPOOL_KEY,
    SpoolIngest,
    ack_channel_name,
)
from areal_tpu.system.streams import (
    Payload,
    WorkerRequestServer,
    ZmqPuller,
    ZmqPusher,
)

logger = logging.getLogger("system.trainer")


@dataclasses.dataclass
class ModelRoleConfig:
    """One model role (actor/critic/ref/reward) hosted by the trainer."""

    # model construction: "hf_dir" (path) or "init" (cfg dict) or "shared"
    init: Dict[str, Any] = dataclasses.field(default_factory=dict)
    backend: str = "jax_train"
    backend_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    train: bool = True


@dataclasses.dataclass
class MFCRuntimeConfig:
    """Interface binding for one MFC name."""

    interface: str = "ppo_actor"
    interface_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    model_name: str = "actor"
    method: str = "train_step"


@dataclasses.dataclass
class TrainerWorkerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    handler: str = "trainer"
    models: Dict[str, ModelRoleConfig] = dataclasses.field(default_factory=dict)
    mfcs: Dict[str, MFCRuntimeConfig] = dataclasses.field(default_factory=dict)
    dataset: Optional[str] = None
    dataset_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    batch_size: int = 8
    ft_spec: FinetuneSpec = dataclasses.field(default_factory=FinetuneSpec)
    tokenizer: Any = None
    # async mode: pull trajectories from rollout workers instead of a dataset
    stream_dataset: bool = False
    realloc_dir: str = "/tmp/areal_tpu/realloc"
    # Weight publish transport. The worker-level default stays "disk" for
    # back-compat with directly constructed configs; the experiment config
    # tree (api.cli_args BaseExperimentConfig.weight_sync) defaults to the
    # streamed transport and threads it through here.
    weight_sync: WeightSyncConfig = dataclasses.field(
        default_factory=lambda: WeightSyncConfig(transport="disk")
    )
    # Unified telemetry (base/telemetry.py): step-phase spans, weight-sync
    # latency gauges, profiler trigger. Off by default — zero overhead.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Goodput ledger (system/goodput.py): compute/comm/data_wait/idle
    # time-in-state counters + live train/achieved_tflops + train/mfu
    # gauges. Off by default — the null ledger costs nothing.
    goodput: GoodputConfig = dataclasses.field(default_factory=GoodputConfig)
    # Sandbox reward fleet (docs/rewards.md): enabled, trainer-side
    # reward interfaces (sync-mode rw_math_code / fused) grade over HTTP
    # instead of executing verification in the trainer process. Off =
    # legacy local grading, bit-identical.
    reward_service: RewardServiceConfig = dataclasses.field(
        default_factory=RewardServiceConfig
    )
    # Durable sample delivery (system/sample_spool.py): knobs for the
    # trainer side of the at-least-once loop — the replay staleness gate
    # and ack-push budgets. Ingest/ack machinery itself is keyed off
    # arriving ``_spool`` metadata, so a worker/trainer config mismatch
    # still settles instead of resending forever.
    durability: DurabilityConfig = dataclasses.field(
        default_factory=DurabilityConfig
    )
    # Compile & HBM observatory (base/compile_watch.py +
    # system/memwatch.py): jit compile-event tracing over the train
    # engine's entry points, HBM gauges/watermarks around the big
    # allocators, and the compile-inflight heartbeat flag the sentinel's
    # trainer_stalled rule reads. Off by default — zero wrappers, zero
    # device polls, scrape bit-identical.
    compile_watch: CompileWatchConfig = dataclasses.field(
        default_factory=CompileWatchConfig
    )
    # Multi-host SPMD (reference global_comm.py:48): dist_world processes —
    # one per host — join one jax.distributed program; rank 0 owns every
    # control-plane socket and broadcasts (request, data) to the others,
    # which execute the same jitted steps in the same order.
    dist_rank: int = 0
    dist_world: int = 1
    # Virtual CPU devices per process for multi-process CPU testing.
    dist_local_devices: Optional[int] = None
    # TPU chip ids this worker may initialize (launcher-assigned partition
    # in decoupled async mode); None = all chips.
    chips: Optional[List[int]] = None


class TrainerWorker:
    def __init__(self, cfg: TrainerWorkerConfig, model_factory=None):
        """``model_factory(role, role_cfg) -> Model`` lets tests inject tiny
        models; the default builds from role_cfg.init (hf dir / config)."""
        self.cfg = cfg
        self.store: Dict[Any, SequenceSample] = {}
        self.models: Dict[str, Model] = {}
        self.interfaces: Dict[str, Any] = {}
        self._mfc_cfg = cfg.mfcs
        self._server: Optional[WorkerRequestServer] = None
        self._dataset = None
        self._data_iter: List[int] = []
        self._epoch = 0
        self._epoch_pos = 0
        self._puller: Optional[ZmqPuller] = None
        self._pull_q: "queue.Queue[SequenceSample]" = queue.Queue()
        self._pull_thread = None
        # Durable-delivery bookkeeping (rank 0, stream mode): idempotent
        # ingest + the per-worker ack pushers (created lazily on first
        # ack for a worker index). _ack_lock serializes the pull thread
        # (stale drops / re-acks) against the serve thread ("clear").
        self._ingest: Optional[SpoolIngest] = None
        self._ack_pushers: Dict[int, ZmqPusher] = {}
        self._ack_lock = threading.Lock()
        self._model_factory = model_factory or self._default_model_factory
        self._exiting = False
        self._weight_publishers: Dict[str, Any] = {}  # role -> publisher
        # Goodput accounting (null until setup() arms it on rank 0).
        self._ledger = goodput_mod.NULL_LEDGER
        self._mfu = None
        self._flops = None

    # ---------------- setup ----------------

    @staticmethod
    def _default_model_factory(role: str, rc: ModelRoleConfig) -> Model:
        from areal_tpu.models import hf as hfmod

        if "hf_dir" in rc.init:
            cfg, params, tok = hfmod.load_hf_model(rc.init["hf_dir"])
            return Model(role, (cfg, params), tokenizer=tok)
        if "ckpt_dir" in rc.init:
            cfg, params = hfmod.load_checkpoint_auto(rc.init["ckpt_dir"])
            return Model(role, (cfg, params))
        if "tiny" in rc.init:  # fabricated test model (reference testing.py)
            import jax

            from areal_tpu.models import transformer
            from areal_tpu.models.config import tiny_config

            kw = dict(rc.init["tiny"])
            seed = kw.pop("seed", 0)
            cfg = tiny_config(**kw)
            params = transformer.init_params(cfg, jax.random.PRNGKey(seed))
            return Model(role, (cfg, params))
        if rc.init.get("null"):  # tokenizer-only role (rule-based reward)
            return Model(role, None)
        raise ValueError(f"role {role}: no model source in init={rc.init}")

    @property
    def _rank0(self) -> bool:
        return self.cfg.dist_rank == 0

    def _bcast(self, obj):
        if self.cfg.dist_world > 1:
            from areal_tpu.parallel import distributed as dist

            return dist.broadcast_pyobj(obj)
        return obj

    def setup(self) -> None:
        cfg = self.cfg
        if cfg.dist_world > 1:
            from areal_tpu.parallel import distributed as dist

            dist.initialize(
                cfg.experiment, cfg.trial, cfg.dist_rank, cfg.dist_world,
                group="trainer", local_device_count=cfg.dist_local_devices,
            )
        for role, rc in cfg.models.items():
            model = self._model_factory(role, rc)
            if model.tokenizer is None:
                model.tokenizer = cfg.tokenizer
            if rc.backend == "null" or model.module is None:
                self.models[role] = model
                continue
            backend = make_backend(rc.backend, **{"train": rc.train,
                                                 **rc.backend_args})
            self.models[role] = backend.initialize(model, cfg.ft_spec)
        for mfc_name, mc in self._mfc_cfg.items():
            self.interfaces[mfc_name] = make_interface(
                mc.interface, **mc.interface_args
            )
        # Reward grading mode for THIS process (rewards/client.py): the
        # sync-mode rw interface's batch_reward calls fan out to the
        # sandbox fleet when the service is enabled; disabled keeps the
        # legacy in-process path bit-identical.
        from areal_tpu.rewards import client as reward_client

        reward_client.configure_service(
            cfg.reward_service, cfg.experiment, cfg.trial
        )
        # Rank 0 owns the data plane and the master's request socket; other
        # ranks receive everything via broadcast.
        if cfg.dataset is not None and self._rank0:
            self._dataset = make_dataset(
                cfg.dataset, tokenizer=cfg.tokenizer, **cfg.dataset_args
            )
            self._reshuffle()
        if cfg.stream_dataset and self._rank0:
            self._puller = ZmqPuller(cfg.experiment, cfg.trial, cfg.handler)
            self._ingest = SpoolIngest(
                staleness_limit=cfg.durability.replay_staleness_limit
            )
            self._pull_thread = threading.Thread(
                target=self._pull_loop, daemon=True
            )
            self._pull_thread.start()
        if self._rank0:
            self._server = WorkerRequestServer(
                cfg.experiment, cfg.trial, cfg.handler
            )
        # Telemetry + profiler trigger: rank 0 only (it owns the control
        # plane; follower ranks mirror its work anyway). With the config
        # absent/disabled, configure() installs the no-op sink and no
        # watcher is created — the serve loop pays nothing.
        self._profiler = None
        # Goodput ledger + live MFU (system/goodput.py): rank 0 only,
        # like the rest of the control plane. Disabled (the default):
        # the null ledger and no FLOPs math on any handler.
        self._ledger = goodput_mod.NULL_LEDGER
        self._mfu = None
        self._flops = None
        if cfg.telemetry.enabled and self._rank0:
            telemetry.configure(
                cfg.experiment, cfg.trial, "trainer", cfg.dist_rank,
                cfg.telemetry,
            )
            self._profiler = telemetry.ProfilerTriggerWatcher(
                cfg.experiment, cfg.trial
            )
            if cfg.goodput.enabled:
                import jax

                from areal_tpu.base import monitor

                self._ledger = goodput_mod.make_ledger(
                    cfg.goodput, telemetry.get()
                )
                self._mfu = goodput_mod.MfuEmitter(
                    telemetry.get(),
                    goodput_mod.resolve_peak_flops(
                        cfg.goodput, str(jax.devices()[0])
                    ),
                    tflops_name="train/achieved_tflops",
                    mfu_name="train/mfu", context="trainer",
                )
                self._flops = monitor.FlopsCounter()
            # Compile & HBM observatory: the module-global facades the
            # train engine's jit sites (backend/jax_train.py) and the
            # weight-publish paths below call through. Disabled config
            # keeps the NULL objects — the wrap/watermark calls resolve
            # to the raw fn / a no-op context.
            compile_watch.configure(cfg.compile_watch, telemetry.get())
            memwatch.configure(cfg.compile_watch, telemetry.get())
        logger.info(
            f"trainer up (rank {cfg.dist_rank}/{cfg.dist_world}): "
            f"models={list(self.models)} mfcs={list(self.interfaces)}"
        )

    def _reshuffle(self):
        rng = np.random.RandomState(self._epoch + 1)
        self._data_iter = list(rng.permutation(len(self._dataset)))
        self._epoch_pos = 0

    def _pull_loop(self):
        while not self._exiting:
            obj = self._puller.pull(timeout_ms=200)
            if obj is not None:
                # Optional durable-spool framing (system/sample_spool.py):
                # popped like the trace key below, absent on non-durable
                # pushes (bit-identical legacy path).
                spool_meta = (
                    obj.pop(SPOOL_KEY, None) if isinstance(obj, dict)
                    else None
                )
                # Optional sample-lineage context pushed by the rollout
                # worker (streams.ZmqPusher): keep it in the sample's
                # METADATA — it survives the master's metadata buffer and
                # this store untouched, so the train step can close the
                # trace with a terminal span (docs/observability.md).
                trace = telemetry.extract_payload(obj)
                s = SequenceSample.from_json_compatible(obj)
                if trace is not None:
                    s.metadata["_trace"] = [trace.as_dict()]
                if spool_meta is not None and self._ingest is not None \
                        and not self._ingest_spooled(s, spool_meta):
                    continue
                self._pull_q.put(s)

    def _ingest_spooled(self, s: SequenceSample, meta: Dict) -> bool:
        """At-least-once ingest decision; False = drop (do not enqueue).

        Duplicates are a NORMAL event here (a resend racing its own ack,
        or a replay of an already-settled record after the ack was lost)
        — dropped idempotently, re-acked when already settled. Replays
        re-enter the staleness gate: the paper's bounded-off-policyness
        contract must hold across a trainer outage too, so a replay
        whose version lag exceeds the bound is durably dropped (counted
        + acked — a drop the worker knows about is not sample loss)."""
        sid = s.ids[0]
        cur = max(
            (m.version.global_step for m in self.models.values()),
            default=0,
        )
        sample_ver = None
        if "version_end" in s.data:
            sample_ver = float(
                np.asarray(s.data["version_end"]).reshape(-1)[0]
            )
        action, ackp = self._ingest.observe(sid, meta, cur, sample_ver)
        if action == "duplicate":
            telemetry.inc("spool/duplicate_dropped")
            if ackp is not None:
                self._send_acks({ackp[0]: [ackp[1]]})
            return False
        if action == "stale":
            telemetry.inc("spool/replay_stale_dropped")
            self._send_acks({ackp[0]: [ackp[1]]})
            return False
        return True

    def _send_acks(self, by_worker: Dict[int, List[int]]) -> None:
        """Push settled seqnos back to each worker's ack channel. Best
        effort by design: a lost ack is recovered by the worker's resend
        timer + this side's settled-duplicate re-ack, so failures are
        logged and dropped rather than retried here."""
        if not by_worker:
            return
        with self._ack_lock:
            for w, seqnos in by_worker.items():
                try:
                    pusher = self._ack_pushers.get(w)
                    if pusher is None:
                        pusher = ZmqPusher(
                            self.cfg.experiment, self.cfg.trial,
                            ack_channel_name(w), timeout=5.0,
                            block_secs=1.0,
                        )
                        self._ack_pushers[w] = pusher
                    pusher.push({"seqnos": [int(s) for s in seqnos]})
                except Exception as e:  # noqa: BLE001 — worker down/respawning
                    logger.warning(
                        f"ack push to rollout worker {w} failed ({e}); "
                        f"its resend timer will recover"
                    )
                    # Drop the pusher: a respawned worker binds a fresh
                    # address under the same key.
                    stale = self._ack_pushers.pop(w, None)
                    if stale is not None:
                        try:
                            stale.close()
                        except Exception:  # noqa: BLE001
                            pass

    # ---------------- handlers ----------------

    def _read_batch(self, n: int) -> Optional[SequenceSample]:
        """Rank-0-only data-plane read (dataset or rollout stream).

        Stream mode returns WHATEVER is available within the wait window —
        possibly fewer than ``n``, possibly None. The master accumulates
        across fetches until its step batch is full (master_worker
        _load_data); returning early keeps this serve loop responsive
        instead of blocking an entire rollout round inside one request.
        (A partial return that the master treated as complete was the
        r2-era hang: buffer gates wait for n_seqs forever.)"""
        if self.cfg.stream_dataset:
            out: List[SequenceSample] = []
            deadline = time.monotonic() + 0.5
            while len(out) < n and time.monotonic() < deadline:
                try:
                    out.append(self._pull_q.get(timeout=0.1))
                except queue.Empty:
                    if out:
                        break
            return SequenceSample.gather(out) if out else None
        idx = []
        while len(idx) < n and self._dataset is not None:
            if self._epoch_pos >= len(self._data_iter):
                self._epoch += 1
                self._reshuffle()
            idx.append(self._data_iter[self._epoch_pos])
            self._epoch_pos += 1
        return SequenceSample.gather([self._dataset[i] for i in idx])

    def _store_batch(self, batch: SequenceSample) -> None:
        for i in range(batch.bs):
            s = batch.select_idx([i])
            self.store[s.ids[0]] = s

    def _handle_fetch(self, p: Payload) -> Any:
        with telemetry.span("trainer/data_wait",
                            stream=self.cfg.stream_dataset) as attrs, \
                self._ledger.state("data_wait"):
            batch = self._read_batch(int(p.data or self.cfg.batch_size))
            attrs["n_seqs"] = batch.bs if batch is not None else 0
        telemetry.set_gauge("trainer/pull_queue_depth",
                            self._pull_q.qsize())
        if batch is not None:
            # Every rank stores the same batch (multi-host: the jitted
            # steps consume identical replicated host inputs per process).
            self._bcast(("fetch", batch))
            self._store_batch(batch)
        return {
            "meta": batch.meta() if batch is not None else None,
            "epoch": self._epoch,
            "epoch_pos": self._epoch_pos,
            "dataset_size": len(self._dataset) if self._dataset else -1,
        }

    def _gather_input(self, ids, input_keys, remap) -> SequenceSample:
        samples = [self.store[i] for i in ids]
        batch = SequenceSample.gather(samples)
        if remap:
            batch = SequenceSample(
                ids=list(batch.ids), keys=set(batch.keys),
                seqlens=dict(batch.seqlens), data=dict(batch.data),
                metadata=dict(batch.metadata),
            )
            batch.remap_keys_(remap)
        return batch

    def _handle_mfc(self, p: Payload) -> Any:
        req = p.data  # {"mfc": name, "ids": [...], "method": ...}
        if req.get("method") == "noop":
            # hook-only request (e.g. a save triggered by the master)
            for hook in p.pre_hooks + p.post_hooks:
                self._run_hook(hook)
            return {"stats": None, "meta": None}
        mfc_name = req["mfc"]
        mc = self._mfc_cfg[mfc_name]
        iface = self.interfaces[mfc_name]
        model = self.models[mc.model_name]
        batch = self._gather_input(req["ids"], req.get("input_keys"),
                                   req.get("input_remap"))
        mb_spec = p.mb_spec or MicroBatchSpec()
        method = req.get("method", mc.method)
        for hook in p.pre_hooks:
            self._run_hook(hook)
        trace_dir = os.environ.get("AREAL_DUMP_TRACE")
        t_mfc_wall = time.time()
        t_mfc = time.monotonic()
        with telemetry.span("trainer/mfc", mfc=mfc_name, method=method,
                            n_seqs=batch.bs), \
                self._ledger.state("compute"):
            if trace_dir:
                # Env-gated per-MFC profiler (reference REAL_DUMP_TRACE,
                # model_worker.py:829 __maybe_profile_rpc): one jax.profiler
                # trace per MFC invocation, viewable in tensorboard/xprof.
                import jax

                out_dir = os.path.join(
                    trace_dir, f"{mfc_name}_{model.version.global_step}"
                )
                with jax.profiler.trace(out_dir):
                    out = getattr(iface, method)(model, batch, mb_spec)
            else:
                out = getattr(iface, method)(model, batch, mb_spec)
        result: Dict[str, Any] = {"stats": None, "meta": None}
        if method == "train_step":
            result["stats"] = out
            self._export_train_stats(mfc_name, out)
            self._emit_mfu(mc.model_name, batch,
                           time.monotonic() - t_mfc)
            self._emit_terminal_spans(
                req["ids"], model, t_mfc_wall, time.monotonic() - t_mfc
            )
        elif out is not None:
            remap = req.get("output_remap") or {}
            if remap:
                out.remap_keys_(remap)
            if method == "generate":
                # Flattened trajectories REPLACE the prompt samples.
                for i in range(out.bs):
                    s = out.select_idx([i])
                    self.store[s.ids[0]] = s
                for old_id in req["ids"]:
                    self.store.pop(old_id, None)
            else:
                for i, sid in enumerate(out.ids):
                    self.store[sid].update_(out.select_idx([i]))
            result["meta"] = out.meta()
        for hook in p.post_hooks:
            self._run_hook(hook)
        return result

    # The divergence signatures that kill RL runs get a distribution view
    # on top of the last-value gauge (suffix _dist: a gauge and a
    # histogram cannot share one Prometheus family name).
    _TRAIN_DIST_KEYS = ("approx_kl", "entropy", "grad_norm",
                        "importance_weight", "clip_ratio")

    def _export_train_stats(self, mfc_name: str,
                            stats: Optional[Dict[str, Any]]) -> None:
        """First-class training-dynamics telemetry per train step
        (docs/observability.md): every train_step scalar becomes a
        ``train/<name>{mfc=...}`` gauge on the scrape — the sentinel's
        rule pack and any external Prometheus reader consume THESE, not
        the stats_tracker/tensorboard keys the master tabulates. No-op
        with telemetry disabled."""
        if not stats or not telemetry.enabled():
            return
        import math

        for k, v in stats.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or not math.isfinite(v):
                continue
            telemetry.set_gauge(f"train/{k}{{mfc={mfc_name}}}", float(v))
            if k in self._TRAIN_DIST_KEYS:
                telemetry.observe(f"train/{k}_dist{{mfc={mfc_name}}}",
                                  float(v))

    def _emit_mfu(self, role: str, batch: SequenceSample,
                  dur_secs: float) -> None:
        """Live achieved-FLOP/s + MFU for one train MFC: packed token
        counts fed through the SAME analytic formulas bench.py reports
        against (base/monitor.py FlopsCounter — the llama formula family
        with the engine's real remat factor), divided by the step's wall
        clock and the chip count. ``train/mfu`` degrades to
        achieved-TFLOP/s-only on unknown device kinds (MfuEmitter).
        No-op with goodput disabled."""
        if self._flops is None or self._mfu is None or dur_secs <= 0:
            return
        engine = self.models[role].module
        cfg = getattr(engine, "cfg", None)
        if cfg is None or not batch.seqlens:
            return
        import jax

        # The MAIN token key, not an arbitrary one: seqlens also carries
        # scalar keys (rewards: [[1]] per sample), and set-ordered
        # iteration could pick one of those — understating the gauges by
        # orders of magnitude, nondeterministically.
        lens = [float(v) for v in batch.total_lens()]
        n_tokens = sum(lens)
        if n_tokens <= 0:
            return
        self._flops.add_train(
            cfg, n_tokens, n_tokens / max(len(lens), 1),
            remat=bool(getattr(engine, "remat", False)),
        )
        self._mfu.emit(
            self._flops.pop() / dur_secs / max(jax.device_count(), 1)
        )

    def _emit_terminal_spans(self, ids, model, t_start: float,
                             dur_secs: float) -> None:
        """Close each traced sample's lineage: a terminal
        ``trainer/train_sample`` span recording WHICH weight version
        trained it — the stitcher (base/telemetry.TraceStitcher) keys the
        prompt→trained latency + stage breakdown off this span. The trace
        is CONSUMED from the stored sample's metadata on emit: several
        TRAIN_STEP MFCs may read the same sample ids in one step
        (actor_train + critic_train), and only the first to train it
        terminates the trace — otherwise every stitched metric would
        double per extra train MFC. No-op with telemetry disabled or for
        untraced samples."""
        if not telemetry.enabled():
            return
        version = model.version.global_step
        for sid in ids:
            s = self.store.get(sid)
            if s is None:
                continue
            tr = (s.metadata.pop("_trace", None) or [None])[0]
            if not isinstance(tr, dict):
                continue
            ctx = telemetry.TraceContext.from_dict(tr)
            if ctx is None:
                continue
            telemetry.add_span(
                "trainer/train_sample", t_start, dur_secs, trace=ctx,
                sample_id=str(sid), weight_version=version,
            )

    def _run_hook(self, hook: Dict) -> None:
        kind = hook.get("kind")
        if kind == "weight_update":
            self.publish_weights(hook.get("role", "actor"))
        elif kind == "save":
            role = hook.get("role", "actor")
            self._save_role(role, hook["path"])
        elif kind == "param_realloc":
            # EMA: target := eta*source + (1-eta)*target (reference ref-EMA)
            import jax

            from areal_tpu.parallel import reshard as rsh

            src = self.models[hook["source"]].module
            dst = self.models[hook["target"]].module
            eta = float(hook.get("eta", 1.0))
            # MFC-boundary reshard: under a heterogeneous per-MFC allocation
            # the source and target roles live on different meshes, so move
            # the source tree into the target's layout on device first — the
            # EMA math then runs entirely on the target's mesh. Same-layout
            # roles hit the zero-copy no-op path (plan.n_moved == 0).
            src_params, plan = rsh.reshard_pytree(
                src.params, rsh.shardings_of(dst.params)
            )
            if plan.n_moved:
                with self._ledger.state("comm"):
                    jax.block_until_ready(src_params)
                logger.info(
                    f"param_realloc reshard {hook['source']}→{hook['target']}: "
                    + plan.describe()
                )
            dst.params = jax.tree.map(
                lambda s, d: (eta * s.astype(np.float32)
                              + (1 - eta) * d.astype(np.float32)).astype(d.dtype),
                src_params, dst.params,
            )
        else:
            raise ValueError(f"unknown hook {hook}")

    def _save_role(self, role: str, path: str, fmt: str = "hf") -> None:
        from areal_tpu.models import hf as hfmod
        from areal_tpu.parallel import distributed as dist

        model = self.models[role]
        engine = model.module
        params = (self._compute_dtype_params(role) if fmt == "native"
                  else engine.params)
        host_params = dist.allgather_params(params)
        if not self._rank0:
            return
        saver = (hfmod.save_native_checkpoint if fmt == "native"
                 else hfmod.save_hf_checkpoint)
        saver(
            host_params, engine.cfg, path,
            meta={"version": model.version.global_step},
        )

    def _compute_dtype_params(self, role: str):
        """The role's params cast (on device) to the compute dtype —
        weight-sync payloads travel in bf16: the generation fleet computes
        in bf16 anyway, and casting before the d2h halves transport bytes
        vs shipping the f32 masters."""
        import jax
        import jax.numpy as jnp

        engine = self.models[role].module
        params = engine.params
        cd = getattr(engine, "compute_dtype", jnp.float32)
        if cd != jnp.float32:
            params = jax.tree.map(
                lambda x: x.astype(cd)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                params,
            )
        return params

    def publish_weights(self, role: str) -> None:
        """The §3.5 weight-sync path: make the role's weights visible to
        the generation fleet and bump names.model_version.

        Transport "stream" (docs/weight_sync.md) hands the tensors to a
        per-role WeightStreamPublisher: servers pull per-tensor chunks
        over ZMQ straight from this process's host cache — no checkpoint
        round-trip through the filesystem. Transport "device" never leaves
        the accelerator: the live params reshard into the generation
        fleet's layout on device (parallel/reshard.py) and servers swap
        them straight out of the publish registry. Transport "disk" is the
        legacy fallback: NATIVE pytree format under the realloc dir
        (models/hf.py save_native_checkpoint — skips HF layout conversion
        both ways; persistent "save" hooks stay HF)."""
        if self.cfg.weight_sync.transport == "stream":
            self._publish_weights_stream(role)
            return
        if self.cfg.weight_sync.transport == "device":
            self._publish_weights_device(role)
            return
        model = self.models[role]
        version = model.version.global_step
        path = os.path.join(self.cfg.realloc_dir, role, str(version))
        t0 = time.monotonic()
        with telemetry.span("trainer/weight_publish", role=role,
                            version=version, transport="disk"), \
                self._ledger.state("comm"), \
                memwatch.watermark("trainer/weight_publish"):
            self._save_role(role, path, fmt="native")
        save_secs = time.monotonic() - t0
        telemetry.set_gauge("trainer/weight_publish_secs", save_secs)
        telemetry.inc("trainer/weight_publishes")
        if not self._rank0:
            return
        # A crashed stream/device-mode predecessor may have left its
        # discovery keys in name_resolve; clear them so the manager's
        # transport auto-detection routes this publish (and all later
        # ones) at the disk checkpoint instead of a dead publisher.
        self._clear_stale_transport_keys(role, keep="disk")
        self._bump_version(role, version, save_secs)
        logger.info(
            f"published {role} weights v{version} -> {path} "
            f"(save {save_secs:.2f}s)"
        )

    def _publish_weights_stream(self, role: str) -> None:
        from areal_tpu.models.hf import flatten_pytree

        model = self.models[role]
        version = model.version.global_step
        t0 = time.monotonic()
        params = self._compute_dtype_params(role)
        if self.cfg.dist_world > 1:
            # Multi-host: every rank joins the gather; only rank 0 owns a
            # publisher, so the others contribute their shards and return.
            from areal_tpu.parallel import distributed as dist

            params = dist.allgather_params(params)
        if not self._rank0:
            return
        pub = self._weight_publishers.get(role)
        if pub is None:
            from areal_tpu.system.weight_stream import WeightStreamPublisher

            pub = WeightStreamPublisher(
                self.cfg.experiment, self.cfg.trial, role,
                chunk_bytes=self.cfg.weight_sync.chunk_mb << 20,
            )
            self._weight_publishers[role] = pub
        # publish() returns the moment the manifest is registered: the d2h
        # gather runs in the publisher's background thread, overlapping the
        # wire leg of tensors already gathered (and the servers' uploads).
        with telemetry.span("trainer/weight_publish", role=role,
                            version=version, transport="stream"), \
                self._ledger.state("comm"), \
                memwatch.watermark("trainer/weight_publish"):
            pub.publish(sorted(flatten_pytree(params).items()), version)
        publish_secs = time.monotonic() - t0
        telemetry.set_gauge("trainer/weight_publish_secs", publish_secs)
        telemetry.inc("trainer/weight_publishes")
        self._clear_stale_transport_keys(role, keep="stream")
        self._bump_version(role, version, publish_secs)
        logger.info(
            f"published {role} weights v{version} -> {pub.endpoint} "
            f"(stream publish {publish_secs:.2f}s; gather continues in "
            f"background)"
        )

    def _publish_weights_device(self, role: str) -> None:
        """Transport "device" (docs/weight_sync.md): reshard the live
        params into the generation fleet's layout ON DEVICE and register
        the result in the in-process publish registry — no d2h, no wire,
        no disk. The fanout payload carries the publication digest out of
        band, so the generation server's swap stays manifest/digest-gated
        exactly like the streamed path."""
        from areal_tpu.parallel import reshard as rsh

        model = self.models[role]
        version = model.version.global_step
        t0 = time.monotonic()
        params = self._compute_dtype_params(role)
        target = self._device_publish_shardings(role, params)
        with telemetry.span("trainer/weight_publish", role=role,
                            version=version, transport="device"), \
                self._ledger.state("comm"), \
                memwatch.watermark("trainer/weight_publish"):
            pub = rsh.publish_device(
                self.cfg.experiment, self.cfg.trial, role, params,
                target_shardings=target, version=version,
                group_mb=self.cfg.weight_sync.transfer_group_mb,
            )
        publish_secs = time.monotonic() - t0
        telemetry.set_gauge("trainer/weight_publish_secs", publish_secs)
        # First-class latency histogram: the device transport's whole
        # point is taking this from minutes to sub-second — the
        # distribution (not just the last value) is the acceptance metric.
        telemetry.observe("trainer/weight_publish_latency_secs",
                          publish_secs)
        telemetry.inc("trainer/weight_publishes")
        if not self._rank0:
            return
        self._clear_stale_transport_keys(role, keep="device")
        self._bump_version(role, version, publish_secs)
        logger.info(
            f"published {role} weights v{version} on device "
            f"({pub.plan.n_moved} leaves moved/"
            f"{len(pub.plan.identical)} zero-copy, "
            f"{publish_secs:.3f}s)"
        )

    def _device_publish_shardings(self, role: str, params):
        """Target layout for a device publish: the gen fleet's spec when
        configured (weight_sync.gen_parallel_spec — decoupled experiments
        thread AllocationMode.gen_spec through), else the ungridded
        single-device layout un-meshed generation servers hold."""
        from areal_tpu.parallel import mesh as pmesh
        from areal_tpu.parallel import reshard as rsh

        gen_spec = self.cfg.weight_sync.gen_parallel_spec
        engine = self.models[role].module
        model_cfg = getattr(engine, "cfg", None)
        if gen_spec and model_cfg is not None:
            mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse(gen_spec))
            return rsh.model_shardings(mesh, model_cfg)
        return rsh.shardings_like(params, rsh.model_shardings(None, None))

    def _clear_stale_transport_keys(self, role: str, keep: str) -> None:
        """Drop the OTHER transports' discovery keys so the manager's
        auto-detection can never steer a fanout at a transport this
        trainer is not publishing on (e.g. a crashed predecessor's dead
        stream endpoint, or a stale device registry descriptor)."""
        stale = {
            "stream": names.weight_stream,
            "device": names.weight_device,
        }
        stale.pop(keep, None)
        for fn in stale.values():
            try:
                name_resolve.delete(
                    fn(self.cfg.experiment, self.cfg.trial, role)
                )
            except Exception:  # noqa: BLE001 — normally absent
                pass

    def _bump_version(self, role: str, version: int,
                      publish_secs: float) -> None:
        # Publish time anchors the end-to-end weight-sync latency metric
        # (publish start → every server swapped; GserverManager reads it).
        name_resolve.add(
            names.model_version_time(
                self.cfg.experiment, self.cfg.trial, role
            ),
            repr(time.time() - publish_secs), replace=True,
        )
        name_resolve.add(
            names.model_version(self.cfg.experiment, self.cfg.trial, role),
            str(version), replace=True,
        )

    def _handle_model_info(self) -> Dict[str, Any]:
        """Model geometry + device info for the master's FLOPs/MFU logging
        (reference FlopsCounter inputs, flops_counter.py:15)."""
        import jax

        from areal_tpu.models.transformer import (
            activated_param_count,
            param_count,
        )

        info: Dict[str, Any] = {
            "n_devices": jax.device_count(),
            "device_kind": str(jax.devices()[0]),
            "roles": {},
        }
        for role, m in self.models.items():
            engine = m.module
            cfg = getattr(engine, "cfg", None)
            if cfg is None:
                continue
            info["roles"][role] = {
                "n_layers": cfg.n_layers, "hidden_dim": cfg.hidden_dim,
                "q_dim": cfg.q_dim, "kv_dim": cfg.kv_dim,
                "intermediate_dim": cfg.intermediate_dim,
                "vocab_size": cfg.vocab_size, "is_critic": cfg.is_critic,
                "n_params": param_count(cfg),
                # Activated params (per-token compute) — for MoE, only
                # top_k of num_experts FFNs run per token; the master's
                # MFU accounting must not count idle expert weights.
                "n_params_activated": activated_param_count(cfg),
                "moe": None if getattr(cfg, "moe", None) is None else {
                    "num_experts": cfg.moe.num_experts,
                    "top_k": cfg.moe.top_k,
                    "routed_intermediate_dim":
                        cfg.moe.routed_intermediate_dim,
                    "shared_intermediate_dim":
                        cfg.moe.shared_intermediate_dim,
                },
                # Remat recomputes activations in backward → 4× forward
                # FLOPs instead of 3× (reference checkpoint_activations
                # factor); the master's MFU math needs to know.
                "remat": bool(getattr(engine, "remat", False)),
            }
        return info

    def _handle_clear(self, p: Payload) -> Any:
        sids = list(p.data or [])
        for sid in sids:
            self.store.pop(sid, None)
        if self._ingest is not None and sids:
            # Freed ids are SETTLED samples (fully consumed by every MFC
            # after the optimizer step committed, or durably dropped by
            # the master's buffer) — the ack point of the at-least-once
            # delivery loop. Rank 0 only: followers replay "clear" for
            # the store pop, but _ingest exists only where the puller is.
            self._send_acks(self._ingest.pop_settled(sids))
        return {"n_stored": len(self.store)}

    # ---------------- checkpoint / restore ----------------
    #
    # Parity: the reference's recover checkpoints save optimizer shards +
    # interface state so a restarted run continues the same trajectory
    # (megatron.py:711-760, master_worker.py:585). One "ckpt" request saves
    # every trainable role's (params, opt_state, version) + per-MFC
    # interface state (kl controller, value RMS) + the dataset cursor.

    def _handle_ckpt(self, p: Payload) -> Any:
        import json

        ckpt_dir = p.data["dir"]
        if self._rank0:
            os.makedirs(ckpt_dir, exist_ok=True)
        meta: Dict[str, Any] = {
            "versions": {}, "epoch": self._epoch, "epoch_pos": self._epoch_pos,
        }
        for role, model in self.models.items():
            engine = model.module
            if hasattr(engine, "save_train_state"):
                # Multi-host: all ranks join the gather; rank 0 writes.
                engine.save_train_state(os.path.join(ckpt_dir, role))
            meta["versions"][role] = model.version.global_step
        if not self._rank0:
            return {"ok": True}
        iface_states = {}
        for mfc_name, iface in self.interfaces.items():
            if hasattr(iface, "state_dict"):
                iface_states[mfc_name] = iface.state_dict()
        # Atomic write: trainer_state.json doubles as the legacy
        # completeness signal (recover.ckpt_is_complete), so a crash
        # mid-dump must leave no torn file behind.
        path = os.path.join(ckpt_dir, "trainer_state.json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"meta": meta, "interfaces": iface_states}, f)
        os.replace(tmp, path)
        logger.info(f"checkpointed trainer state -> {ckpt_dir}")
        return {"ok": True}

    def _handle_restore(self, p: Payload) -> Any:
        import json

        ckpt_dir = p.data["dir"]
        with open(os.path.join(ckpt_dir, "trainer_state.json")) as f:
            d = json.load(f)
        meta = d["meta"]
        for role, model in self.models.items():
            engine = model.module
            role_dir = os.path.join(ckpt_dir, role)
            if hasattr(engine, "load_train_state") and os.path.isdir(role_dir):
                engine.load_train_state(role_dir)
            model.version.global_step = int(meta["versions"].get(role, 0))
        for mfc_name, st in d["interfaces"].items():
            iface = self.interfaces.get(mfc_name)
            if iface is not None and hasattr(iface, "load_state_dict"):
                iface.load_state_dict(st)
        self._epoch = int(meta["epoch"])
        self._epoch_pos = int(meta["epoch_pos"])
        if self._dataset is not None:
            # Same seed ⇒ same permutation; restoring (epoch, pos) resumes
            # the dataset exactly where the checkpoint left it, so consumed
            # samples are not retrained (reference hash_vals_to_ignore).
            rng = np.random.RandomState(self._epoch + 1)
            self._data_iter = list(rng.permutation(len(self._dataset)))
        logger.info(f"restored trainer state from {ckpt_dir}")
        return {"ok": True, "versions": meta["versions"]}

    # ---------------- loop ----------------

    def _dispatch(self, p: Payload) -> None:
        """Execute one request (all ranks run this identically)."""
        try:
            if p.handle_name == "fetch":
                p.output = self._handle_fetch(p)
            elif p.handle_name == "mfc":
                p.output = self._handle_mfc(p)
            elif p.handle_name == "clear":
                p.output = self._handle_clear(p)
            elif p.handle_name == "version":
                p.output = {
                    r: m.version.global_step for r, m in self.models.items()
                }
            elif p.handle_name == "model_info":
                p.output = self._handle_model_info()
            elif p.handle_name == "ckpt":
                p.output = self._handle_ckpt(p)
            elif p.handle_name == "restore":
                p.output = self._handle_restore(p)
            elif p.handle_name == "exit":
                p.output = "bye"
                self._exiting = True
            else:
                raise ValueError(f"unknown handle {p.handle_name}")
        except Exception as e:  # noqa: BLE001 — surfaced to the master
            import traceback

            p.exception = f"{e}\n{traceback.format_exc()}"
            logger.error(f"handler {p.handle_name} failed: {p.exception}")

    def serve_once(self, timeout_ms: int = 100) -> bool:
        p = self._server.poll(timeout_ms)
        if p is None:
            return False
        if p.handle_name != "fetch":
            # _handle_fetch broadcasts its own (request, batch) pair after
            # the rank-0-only data read; everything else replays verbatim.
            self._bcast(("cmd", p.handle_name, p.data, p.mb_spec,
                         p.pre_hooks, p.post_hooks))
        self._dispatch(p)
        self._server.reply(p)
        return True

    def _follow_once(self) -> None:
        """Rank > 0: receive one broadcast command and replay it."""
        from areal_tpu.parallel import distributed as dist

        msg = dist.broadcast_pyobj(None)
        if msg[0] == "fetch":
            self._store_batch(msg[1])
            return
        _, handle_name, data, mb_spec, pre, post = msg
        p = Payload(handler=self.cfg.handler, handle_name=handle_name,
                    data=data, mb_spec=mb_spec, pre_hooks=pre,
                    post_hooks=post)
        self._dispatch(p)
        if p.exception:
            # Deterministic errors fail identically on every rank; mirroring
            # rank 0 (catch, log, keep serving) keeps the group in lockstep.
            # But a rank-LOCAL failure of a state-mutating handler (mfc
            # optimizer step, restore, clear) means this rank's params/state
            # now diverge from the group — continuing would train silently
            # corrupted. Fail loudly instead; the launcher's child monitor
            # tears the run down.
            if handle_name in ("mfc", "restore", "clear"):
                raise RuntimeError(
                    f"rank {self.cfg.dist_rank} replay of state-mutating "
                    f"{handle_name} failed — exiting to avoid silent SPMD "
                    f"divergence: {p.exception}"
                )
            logger.error(
                f"rank {self.cfg.dist_rank} replay of {handle_name} failed "
                f"(read-only; continuing to stay in sync): {p.exception}"
            )

    def run(self) -> None:
        from areal_tpu.system.worker_base import WorkerControl

        self.setup()
        if self._rank0:
            # Lifecycle FSM endpoint (reference worker_base.py:474); only
            # rank 0 serves it — pausing rank 0 stalls the whole SPMD group
            # at the next broadcast, which is exactly pause semantics.
            # Compile-aware liveness: the heartbeat thread publishes
            # names.compile_inflight while a jit compile is in progress
            # so the sentinel's trainer_stalled rule can tell a warmup
            # compile from a wedge (the NULL watch's inflight() is a
            # constant False — zero traffic when disabled).
            ctrl = WorkerControl(
                self.cfg.experiment, self.cfg.trial, self.cfg.handler,
                inflight_fn=compile_watch.inflight,
            )
            # Liveness: the control heartbeat also keeps the trainer's
            # stream advertisements leased (request ROUTER + trajectory
            # puller) — a SIGKILLed trainer's stale addresses expire
            # instead of swallowing a recovered master's requests; the
            # value rides along so a lapsed lease re-registers.
            if self._server is not None:
                ctrl.lease(self._server._key, self._server._addr)
            if self._puller is not None:
                ctrl.lease(self._puller._key, self._puller._addr)
            while not self._exiting:
                ctrl.step(lambda: {"roles": sorted(self.models)})
                if ctrl.should_exit:
                    break
                if self._profiler is not None:
                    # Operator-requested jax.profiler capture (rate-limited
                    # name-resolve poll; docs/observability.md).
                    self._profiler.poll()
                self.serve_once(timeout_ms=100)
                # Accrue the in-progress state (idle between requests)
                # so the scrape moves even when no handler runs.
                self._ledger.poll()
                # HBM gauges piggyback on the serve cadence (rate-limited
                # inside the watch; the NULL watch is a no-op).
                memwatch.sample()
                telemetry.set_gauge("trainer/store_size", len(self.store))
            ctrl.close()
        else:
            while not self._exiting:
                self._follow_once()
        if self._server:
            self._server.close()
        if self._pull_thread is not None:
            # _exiting is set; the loop exits within one 200ms poll. Join
            # before close — destroying the socket under a live poll
            # raises ENOTSOCK in the thread.
            self._pull_thread.join(timeout=2.0)
        if self._puller:
            self._puller.close()
        for pusher in self._ack_pushers.values():
            pusher.close()
        for pub in self._weight_publishers.values():
            pub.close()
        self._ledger.flush()
        memwatch.shutdown()
        compile_watch.shutdown()
        telemetry.shutdown()  # final flush to the aggregator
