"""Master worker — owns the training loop and DFG traversal.

Parity target: ``realhf/system/master_worker.py:49`` +
``function_executor.py:24`` + ``model_function_call.py:54``: per training
step, spawn one asyncio task per MFC plus a data-loading task; each MFC
task blocks on the metadata buffer until its input keys are ready for
n_seqs samples, dispatches the call to the trainer over ZMQ, and amends the
buffer with the outputs. Save/eval frequency control via timeutil; epoch
accounting from the trainer's fetch replies.

TPU-first simplifications: no DP dispatch/redistribution planning (the
trainer is one SPMD process — GSPMD does the sharding the reference's
RedistribPlanner computed), and requests go to a single trainer handler per
model role group.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from types import SimpleNamespace
from typing import Any, Dict, List, Optional

from areal_tpu.api.data import SequenceSample
from areal_tpu.api.dfg import (
    DataFlowGraph,
    MFCDef,
    MFCInterfaceType,
    ParamReallocHook,
    WeightUpdateHook,
)
from areal_tpu.base import logging, telemetry
from areal_tpu.base.stats_tracker import StatsTracker
from areal_tpu.base.timeutil import FrequencyControl
from areal_tpu.system.buffer import AsyncSequenceBuffer
from areal_tpu.system.streams import MasterRequestStream, Payload

logger = logging.getLogger("system.master")


# Canonical home is the dependency-free api.train_config; re-exported here
# because this module historically defined it.
from areal_tpu.api.train_config import (  # noqa: E402,F401
    CompileWatchConfig,
    DurabilityConfig,
    ExperimentSaveEvalControl,
    GoodputConfig,
    SentinelConfig,
    TelemetryConfig,
)


@dataclasses.dataclass
class MasterWorkerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    trainer_handler: str = "trainer"
    train_batch_size: int = 8
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    save_dir: str = "/tmp/areal_tpu/ckpt"
    # async mode: generation happens outside the DFG (rollout workers)
    src_is_stream: bool = False
    # observability (reference master_worker.py:291-350)
    tensorboard_path: Optional[str] = None
    wandb_mode: str = "disabled"
    # Unified telemetry (base/telemetry.py): the master hosts the
    # cross-worker aggregator (telemetry.jsonl + tensorboard mirror +
    # optional Prometheus http port). Off by default.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Training-health sentinel (system/sentinel.py): hosted inside the
    # aggregator above; requires telemetry. Off by default — nothing is
    # constructed and the merged scrape is bit-identical.
    sentinel: SentinelConfig = dataclasses.field(
        default_factory=SentinelConfig
    )
    # Goodput ledger (system/goodput.py): when enabled the aggregator
    # hosts the fleet-goodput stitcher (useful chip-seconds / total,
    # split trainer vs generation) on the merged scrape. Off by default.
    goodput: GoodputConfig = dataclasses.field(
        default_factory=GoodputConfig
    )
    # Durable sample delivery (system/sample_spool.py): the master's
    # interest is indirect — the freed-id forwarding below is the ack
    # trigger, and with durability armed the sentinel gains the
    # sample_loss absence rule on spool acks.
    durability: DurabilityConfig = dataclasses.field(
        default_factory=DurabilityConfig
    )
    # Compile & HBM observatory (base/compile_watch.py): the master's
    # interest is rule-pack arming — with the observatory on, the
    # sentinel gains the recompile_storm / hbm_pressure / compile_stall
    # pack over the series the chip-bearing workers export.
    compile_watch: CompileWatchConfig = dataclasses.field(
        default_factory=CompileWatchConfig
    )
    # recover checkpoints (RecoverInfo + trainer train-state) live here
    recover_dir: str = ""
    # resume from the latest recover checkpoint at startup
    recover: bool = False
    keep_recover_ckpts: int = 2


class MasterWorker:
    def __init__(self, cfg: MasterWorkerConfig, dfg: DataFlowGraph):
        self.cfg = cfg
        self.dfg = dfg
        # Every node reads its inputs from the buffer once.
        self.buffer = AsyncSequenceBuffer(n_rpcs_reading=len(dfg.nodes))
        self.stream: Optional[MasterRequestStream] = None
        self.step = 0
        self.epoch = 0
        self._train_nodes = [
            n for n in dfg.nodes.values()
            if n.interface_type == MFCInterfaceType.TRAIN_STEP
        ]
        self._gen_nodes = [
            n for n in dfg.nodes.values()
            if n.interface_type == MFCInterfaceType.GENERATE
        ]
        self.stats = StatsTracker()
        self._save_ctl = FrequencyControl(
            freq_step=cfg.exp_ctrl.save_freq_steps,
        )
        self._ckpt_ctl = FrequencyControl(
            freq_step=cfg.exp_ctrl.ckpt_freq_steps,
            freq_sec=cfg.exp_ctrl.ckpt_freq_secs,
        )
        self._stats_history: List[Dict[str, float]] = []

    # ---------------- setup ----------------

    def setup(self) -> None:
        from areal_tpu.base import monitor
        from areal_tpu.system.worker_base import WorkerControl

        # Lifecycle FSM endpoint (reference worker_base.py:474): the
        # launcher/operator can pause/resume/exit/status this worker
        # between training steps.
        self.ctrl = WorkerControl(
            self.cfg.experiment, self.cfg.trial, "master"
        )
        # Graceful drain (system/supervisor.py drain_experiment): dump a
        # recover checkpoint OUT-OF-BAND of the ckpt cadence. Served
        # between steps (and while paused), so no MFC is in flight when
        # it runs — the trainer RPC below is safe.
        self.ctrl.on_command("checkpoint", self._on_demand_ckpt)
        # The aggregator MUST exist before any worker's pusher looks for
        # it, and before the master's own telemetry configures — so it is
        # the first telemetry object up. Disabled config: nothing starts.
        self._aggregator = None
        self._sentinel = None
        if self.cfg.telemetry.enabled:
            import os

            # Default next to the tensorboard stream (the log dir), per
            # the TelemetryConfig contract; the checkpoint dir is only
            # the last resort for bare configs with no tensorboard path.
            jsonl = self.cfg.telemetry.jsonl_path or os.path.join(
                os.path.dirname(self.cfg.tensorboard_path)
                if self.cfg.tensorboard_path else self.cfg.save_dir,
                "telemetry.jsonl",
            )
            if self.cfg.sentinel.enabled:
                # Training-health sentinel (docs/observability.md
                # §Alerting): hosted in the aggregator below — fed every
                # ingested snapshot, ticked from the ingest loop, no
                # threads of its own. alerts.jsonl and the evidence dir
                # default next to telemetry.jsonl.
                from areal_tpu.system.sentinel import (
                    Sentinel,
                    rules_from_config,
                )

                log_dir = os.path.dirname(jsonl) or "."
                self._sentinel = Sentinel(
                    self.cfg.sentinel, self.cfg.experiment, self.cfg.trial,
                    # The durability pack (sample_loss absence on spool
                    # acks) arms only alongside the durable spool — on a
                    # non-durable run the series never exists and an
                    # absence rule would false-fire.
                    rules=rules_from_config(
                        self.cfg.sentinel,
                        durability_enabled=self.cfg.durability.enabled,
                        # Same gating story for the compile/HBM pack: its
                        # series exist only with the observatory armed.
                        compile_watch_enabled=self.cfg.compile_watch.enabled,
                    ),
                    alerts_path=(self.cfg.sentinel.alerts_path
                                 or os.path.join(log_dir, "alerts.jsonl")),
                    evidence_dir=(self.cfg.sentinel.evidence_dir
                                  or os.path.join(log_dir, "evidence")),
                )
            goodput_stitcher = None
            if self.cfg.goodput.enabled:
                # Fleet goodput (docs/observability.md §Goodput): derived
                # from the worker ledgers' counters as they ingest; the
                # merged scrape gains the "fleet" pseudo-worker row.
                from areal_tpu.system.goodput import FleetGoodput

                goodput_stitcher = FleetGoodput()
            self._aggregator = telemetry.TelemetryAggregator(
                self.cfg.experiment, self.cfg.trial, jsonl_path=jsonl,
                http_port=self.cfg.telemetry.http_port,
                # Stitched sample-lineage traces (one line per trained
                # sample); defaults next to telemetry.jsonl.
                traces_path=self.cfg.telemetry.traces_path,
                stitch_grace_secs=self.cfg.telemetry.stitch_grace_secs,
                sentinel=self._sentinel,
                goodput=goodput_stitcher,
            )
            telemetry.configure(
                self.cfg.experiment, self.cfg.trial, "master", 0,
                self.cfg.telemetry,
            )
        self.stream = MasterRequestStream(
            self.cfg.experiment, self.cfg.trial, [self.cfg.trainer_handler]
        )
        self._model_info = self.stream.call(
            self.cfg.trainer_handler, "model_info", None
        )
        self._peak_flops = monitor.device_peak_flops(
            self._model_info.get("device_kind", "")
        )
        self._flops = monitor.FlopsCounter()
        self._writer = monitor.MetricWriter(
            tensorboard_path=self.cfg.tensorboard_path,
            wandb_mode=self.cfg.wandb_mode,
        )
        if self._aggregator is not None:
            # Mirror per-worker telemetry scalars into the same tensorboard
            # stream as the training stats (telemetry/{worker}/{metric}).
            self._aggregator.set_metric_writer(self._writer)
        if self.cfg.recover and self.cfg.recover_dir:
            self._try_recover()

    def _try_recover(self) -> None:
        """Resume from the latest recover checkpoint (reference
        master_worker.py:585 dump / recover.discover_ckpt)."""
        from areal_tpu.base import recover

        info = recover.load(self.cfg.recover_dir)
        ckpt = recover.discover_ckpt(self.cfg.recover_dir)
        if info is None or ckpt is None:
            logger.info("recover requested but no checkpoint found; "
                        "starting fresh")
            return
        self.step = info.last_step_info.global_step
        self.epoch = info.last_step_info.epoch
        if info.save_ctl_states.get("save"):
            self._save_ctl.load_state_dict(info.save_ctl_states["save"])
        if info.ckpt_ctl_states.get("ckpt"):
            self._ckpt_ctl.load_state_dict(info.ckpt_ctl_states["ckpt"])
        reply = self.stream.call(
            self.cfg.trainer_handler, "restore", {"dir": ckpt}
        )
        logger.info(
            f"recovered at step {self.step} epoch {self.epoch} from {ckpt} "
            f"(model versions: {reply.get('versions')})"
        )

    def _on_demand_ckpt(self, payload=None) -> Dict[str, Any]:
        if not self.cfg.recover_dir:
            return {"saved": False, "reason": "no recover_dir configured"}
        ckpt_dir = self._do_ckpt()
        return {"saved": True, "dir": ckpt_dir, "step": self.step,
                "epoch": self.epoch}

    def _do_ckpt(self) -> Optional[str]:
        from areal_tpu.base import recover

        if not self.cfg.recover_dir:
            return None
        name = recover.ckpt_dirname(self.epoch, self.step, self.step)
        ckpt_dir = f"{self.cfg.recover_dir}/{name}"
        self.stream.call(self.cfg.trainer_handler, "ckpt", {"dir": ckpt_dir})
        # Terminal sentinel AFTER the trainer acked the save: a crash
        # mid-save leaves the dir incomplete and discover_ckpt skips it.
        recover.mark_ckpt_complete(ckpt_dir)
        si = recover.StepInfo(self.epoch, self.step, self.step)
        recover.dump(self.cfg.recover_dir, recover.RecoverInfo(
            recover_start=si, last_step_info=si,
            # Frequency-controller states: without them a recovered run
            # re-anchors its save/ckpt cadence at the restart point
            # (reference RecoverInfo.save_ctl_states, recover.py:26).
            save_ctl_states={"save": self._save_ctl.state_dict()},
            ckpt_ctl_states={"ckpt": self._ckpt_ctl.state_dict()},
        ))
        # GC old recover ckpts (they are large: params + optimizer state).
        import os
        import shutil

        entries = []
        for n in os.listdir(self.cfg.recover_dir):
            st = recover.parse_ckpt_dirname(n)
            if st is not None:
                entries.append((st.global_step, n))
        for _, n in sorted(entries)[: -self.cfg.keep_recover_ckpts]:
            shutil.rmtree(f"{self.cfg.recover_dir}/{n}", ignore_errors=True)
        return ckpt_dir

    def _count_mfc_flops(self, node: MFCDef, metas: List[SequenceSample]) -> None:
        """Analytic FLOPs for one MFC from input metadata (lengths only)."""
        info = self._model_info.get("roles", {}).get(node.model_name)
        if info is None or not metas:
            return
        key = next(iter(metas[0].seqlens))
        lens = [sum(m.seqlens[key][0]) for m in metas]
        n_tokens = float(sum(lens))
        avg = n_tokens / max(len(lens), 1)

        moe_info = info.get("moe")

        class _C:  # adapter: monitor formulas take config-like fields
            n_layers = info["n_layers"]
            hidden_dim = info["hidden_dim"]
            q_dim = info["q_dim"]
            kv_dim = info["kv_dim"]
            intermediate_dim = info["intermediate_dim"]
            vocab_size = info["vocab_size"]
            is_critic = info["is_critic"]
            # Activated-compute geometry: monitor switches the MLP term
            # to top_k routed + shared expert when this is set.
            moe = (
                None if moe_info is None
                else SimpleNamespace(**moe_info)
            )

        if node.interface_type == MFCInterfaceType.TRAIN_STEP:
            self._flops.add_train(
                _C, n_tokens, avg, remat=info.get("remat", False)
            )
        else:
            self._flops.add_inf(_C, n_tokens, avg)

    # ---------------- per-step DFG traversal ----------------

    async def _load_data(self) -> None:
        """Fill one step's batch from the trainer's dataset/stream.

        Stream mode may return PARTIAL (or empty) fetches — keep fetching
        until train_batch_size samples landed in the buffer; a single
        partial fetch treated as complete deadlocks every MFC gate
        (n_seqs never satisfied) while the trainer sits idle."""
        got = 0
        while got < self.cfg.train_batch_size:
            reply = await asyncio.to_thread(
                self.stream.call, self.cfg.trainer_handler, "fetch",
                self.cfg.train_batch_size - got,
            )
            self.epoch = reply["epoch"]
            self._dataset_size = reply["dataset_size"]
            meta: Optional[SequenceSample] = reply["meta"]
            if meta is None or meta.bs == 0:
                await asyncio.sleep(0.2)
                continue
            singles = [meta.select_idx([i]) for i in range(meta.bs)]
            await self.buffer.put_batch(singles)
            got += meta.bs

    def _hook_dicts(self, node: MFCDef, post: bool) -> List[Dict]:
        out = []
        for h in node.post_hooks if post else node.pre_hooks:
            if isinstance(h, WeightUpdateHook):
                out.append({"kind": "weight_update", "role": h.role})
            elif isinstance(h, ParamReallocHook):
                out.append({
                    "kind": "param_realloc", "source": h.source,
                    "target": h.target, "eta": h.eta,
                })
        return out

    async def _run_mfc(self, node: MFCDef) -> None:
        with telemetry.span("master/mfc_gate", mfc=node.name):
            metas = await self.buffer.get_batch_for_rpc(
                node.name, set(node.input_keys), node.n_seqs
            )
        t_mfc = time.monotonic()
        self._count_mfc_flops(node, metas)
        ids = [m.ids[0] for m in metas]
        payload = Payload(
            handler=self.cfg.trainer_handler,
            handle_name="mfc",
            data={
                "mfc": node.name,
                "ids": ids,
                "method": node.interface_type.value,
                "input_keys": list(node.input_keys),
                "input_remap": node.input_key_remap,
                "output_remap": node.output_key_remap,
            },
            mb_spec=node.mb_spec,
            pre_hooks=self._hook_dicts(node, post=False),
            post_hooks=self._hook_dicts(node, post=True),
        )
        rid = self.stream.post(payload)
        with telemetry.span("master/mfc_exec", mfc=node.name,
                            n_seqs=len(ids)):
            reply = (await asyncio.to_thread(self.stream.gather, [rid]))[0]
        out = reply.output
        if node.interface_type == MFCInterfaceType.TRAIN_STEP:
            if out["stats"]:
                self.stats.scalar(**{
                    f"{node.name}/{k}": v for k, v in out["stats"].items()
                })
        elif node.interface_type == MFCInterfaceType.GENERATE:
            # Trajectories replace the prompt slots (flattened groups).
            new_meta: SequenceSample = out["meta"]
            await self.buffer.drop_ids(ids)
            singles = [new_meta.select_idx([i]) for i in range(new_meta.bs)]
            await self.buffer.put_batch(singles)
            await self.buffer.mark_read(
                [s.ids[0] for s in singles], node.name
            )
        else:
            if out["meta"] is not None:
                await self.buffer.amend_batch(out["meta"])
        self.stats.scalar(**{
            f"timeperf/{node.name}": time.monotonic() - t_mfc
        })

    async def _execute_step(self) -> None:
        tasks = [self._load_data()]
        tasks += [self._run_mfc(n) for n in self.dfg.nodes.values()]
        await asyncio.gather(*tasks)

    # ---------------- main loop ----------------

    def should_stop(self) -> bool:
        ctrl = self.cfg.exp_ctrl
        if ctrl.benchmark_steps is not None and self.step >= ctrl.benchmark_steps:
            return True
        return self.epoch >= ctrl.total_train_epochs

    def run(self) -> Dict[str, Any]:
        # One event loop for the whole experiment: the buffer's asyncio
        # primitives bind to the loop that first touches them.
        return asyncio.run(self._run_async())

    async def _run_async(self) -> Dict[str, Any]:
        self.setup()
        t_start = time.monotonic()
        while not self.should_stop():
            # Serve the control channel between steps; pause blocks here.
            await asyncio.to_thread(
                self.ctrl.step,
                lambda: {"step": self.step, "epoch": self.epoch},
            )
            if self.ctrl.should_exit:
                logger.info("master: exit requested via control channel")
                break
            t0 = time.monotonic()
            with telemetry.span("master/step", step=self.step):
                await self._execute_step()
            self.step += 1
            step_stats = self.stats.export(reset=True)
            dt = time.monotonic() - t0
            step_stats["timeperf/e2e"] = dt
            # Analytic TFLOP/s per chip + MFU (reference master_worker.py:497
            # tabulates the FlopsCounter the same way).
            n_chips = max(self._model_info.get("n_devices", 1), 1)
            flops = self._flops.pop()
            if flops > 0:
                per_chip = flops / dt / n_chips
                step_stats["timeperf/tflops_per_chip"] = per_chip / 1e12
                if self._peak_flops:
                    step_stats["timeperf/mfu"] = per_chip / self._peak_flops
            self._stats_history.append(step_stats)
            self._writer.write(step_stats, self.step)
            # Step wall time on the scrape (throughput-regression rules)
            # and a DIRECT sentinel feed: the master hosts the engine
            # in-process, so its per-step series skip the flush latency
            # every other worker's snapshots pay. Feed only — rule
            # evaluation (and its evidence-capture I/O) belongs to the
            # aggregator's ingest thread, never the step loop.
            telemetry.set_gauge("master/step_secs", dt)
            if self._sentinel is not None:
                # Same "kind:index" identity the flushed copy arrives
                # under, so the direct feed and the aggregator ingest
                # share ONE source slot instead of double-counting.
                self._sentinel.feed("master:0", {
                    "master/step_secs": dt,
                    "master/step": float(self.step),
                })
            logger.info(
                f"step {self.step} epoch {self.epoch} "
                f"({step_stats['timeperf/e2e']:.2f}s): "
                + " ".join(
                    f"{k}={v:.4g}" for k, v in sorted(step_stats.items())
                    if "/" in k
                )
            )
            if self._save_ctl.check(epochs=self.epoch, steps=self.step):
                await asyncio.to_thread(self._request_save)
            if self._ckpt_ctl.check(epochs=self.epoch, steps=self.step):
                await asyncio.to_thread(self._do_ckpt)
            # post-step GC: tell the trainer which samples were fully
            # consumed so its tensor store can drop them.
            freed = await self.buffer.pop_freed()
            await asyncio.to_thread(
                self.stream.call, self.cfg.trainer_handler, "clear", freed
            )
        total = time.monotonic() - t_start
        logger.info(f"experiment complete: {self.step} steps in {total:.1f}s")
        # Published BEFORE the trainer is told to exit: the launcher's
        # supervisor consults this (timestamped) marker when it sees a
        # child die, so the commanded end-of-run trainer exit is never
        # classified as a stateful-worker death and escalated while this
        # thread is still in its teardown tail.
        try:
            import json as _json

            from areal_tpu.base import name_resolve, names
            name_resolve.add(
                names.experiment_status(self.cfg.experiment, self.cfg.trial),
                _json.dumps({"status": "finishing", "ts": time.time()}),
                replace=True, delete_on_exit=False,
            )
        except Exception:  # noqa: BLE001 — marker is advisory
            pass
        await asyncio.to_thread(
            self.stream.call, self.cfg.trainer_handler, "exit"
        )
        telemetry.shutdown()  # final master flush into the aggregator
        if self._aggregator is not None:
            self._aggregator.close()
        self._writer.close()
        self.ctrl.close()
        return {"steps": self.step, "stats": self._stats_history}

    def _request_save(self) -> None:
        rids = [
            self.stream.post(Payload(
                handler=self.cfg.trainer_handler, handle_name="mfc",
                data={"mfc": node.name, "ids": [], "method": "noop"},
                post_hooks=[{
                    "kind": "save", "role": node.model_name,
                    "path": f"{self.cfg.save_dir}/{node.model_name}/step{self.step}",
                }],
            ))
            for node in self._train_nodes
        ]
        self.stream.gather(rids)
