"""Chunked interruptible generation client with per-token version tracking.

Parity target: ``realhf/system/partial_rollout.py:29``
(PartialRolloutManager): split each generation into chunks so weight
updates only ever interrupt a chunk; carry accumulated tokens + logprobs
across chunks; sticky-route to the same server while the version is
unchanged; group ``group_size`` samples per prompt into one bundle with
``version_start``/``version_end`` per sample (the decoupled-loss inputs).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Dict, List, Optional

import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.base import logging

logger = logging.getLogger("system.partial_rollout")


@dataclasses.dataclass
class GenResult:
    output_ids: List[int]
    output_logprobs: List[float]
    version_start: int
    version_end: int
    n_chunks: int


class PartialRolloutClient:
    """Async client: one ``generate`` = N chunked HTTP calls routed through
    the gserver manager."""

    def __init__(self, manager_url: str, session, chunk_tokens: int = 128):
        self.manager_url = manager_url
        self.session = session  # aiohttp.ClientSession
        self.chunk_tokens = chunk_tokens

    async def _schedule(self) -> Dict:
        async with self.session.post(
            f"{self.manager_url}/schedule_request", json={}
        ) as r:
            return await r.json()

    async def _release(self, route: Dict) -> None:
        await self.session.post(
            f"{self.manager_url}/release",
            json={"lease_id": route.get("lease_id"), "url": route["url"]},
        )

    async def _renew(self, route: Dict) -> None:
        lid = route.get("lease_id")
        if lid is not None:
            await self.session.post(f"{self.manager_url}/renew",
                                    json={"lease_id": lid})

    async def generate_one(
        self,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        eos_token_id: int = 1,
    ) -> GenResult:
        acc_ids: List[int] = []
        acc_lps: List[float] = []
        version_start: Optional[int] = None
        version_end = 0
        n_chunks = 0
        # The lease is held for the whole sticky lifetime (not just the
        # first chunk) so the manager's least_requests accounting sees the
        # server as busy; renewed each chunk, released on route drop/end.
        route: Optional[Dict] = None
        rid = uuid.uuid4().hex  # keys the server's persistent decode state
        try:
            while len(acc_ids) < gconfig.max_new_tokens:
                # sticky routing while version unchanged (reference :181)
                if route is None:
                    route = await self._schedule()
                url = route["url"]
                left = gconfig.max_new_tokens - len(acc_ids)
                body = {
                    "rid": rid,
                    "tokens_done": len(acc_ids),
                    "prompt_ids": list(prompt_ids) + acc_ids,
                    "gconfig": {
                        **dataclasses.asdict(gconfig),
                        "max_new_tokens": min(self.chunk_tokens, left),
                        "n": 1,
                    },
                    "max_tokens": min(self.chunk_tokens, left),
                }
                async with self.session.post(f"{url}/generate",
                                             json=body) as r:
                    out = await r.json()
                n_chunks += 1
                acc_ids += list(out["output_ids"])
                acc_lps += list(out["output_logprobs"])
                v = int(out["version"])
                if version_start is None:
                    version_start = v
                version_end = v
                if out["finished"] or not out["output_ids"]:
                    break
                if v == route.get("version", v):
                    await self._renew(route)  # stay sticky
                else:
                    await self._release(route)
                    route = None  # version moved: re-schedule next chunk
        finally:
            if route is not None:
                await self._release(route)
        return GenResult(
            output_ids=acc_ids,
            output_logprobs=acc_lps,
            version_start=version_start or 0,
            version_end=version_end,
            n_chunks=n_chunks,
        )

    async def generate_group(
        self,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        group_size: int,
        eos_token_id: int = 1,
    ) -> List[GenResult]:
        import asyncio

        return list(await asyncio.gather(*[
            self.generate_one(prompt_ids, gconfig, eos_token_id)
            for _ in range(group_size)
        ]))


def trajectory_from_gen(
    qid: str,
    j: int,
    prompt_ids: np.ndarray,
    res: GenResult,
    task: str = "math",
    task_id: int = 0,
    eos_token_id: int = 1,
):
    """One flattened trajectory SequenceSample from a chunked generation
    (same key layout as algorithms.ppo.trajectories_from_gen_output)."""
    import time as _time

    from areal_tpu.api.data import SequenceSample

    gl = max(len(res.output_ids), 1)
    toks = np.concatenate([
        prompt_ids, np.asarray(res.output_ids[:gl], np.int32)
    ]) if res.output_ids else np.concatenate([prompt_ids, [eos_token_id]])
    P = len(prompt_ids)
    gl = len(toks) - P
    lps = np.concatenate([
        np.zeros(P, np.float32),
        np.asarray((res.output_logprobs + [0.0])[:gl], np.float32),
    ])
    no_eos = float(eos_token_id not in toks[P:])
    return SequenceSample.from_default(
        ids=[f"{qid}@{j}"],
        data={
            "packed_input_ids": toks.astype(np.int32),
            "prompt_mask": np.concatenate([
                np.ones(P, np.int32), np.zeros(gl, np.int32)
            ]),
            "packed_logprobs": lps,
            "seq_no_eos_mask": np.asarray([no_eos], np.float32),
            "task_ids": np.asarray([task_id], np.int32),
            "version_start": np.asarray([res.version_start], np.int32),
            "version_end": np.asarray([res.version_end], np.int32),
            "birth_time": np.asarray([_time.time()], np.float64),
        },
        seqlens=[len(toks)],
        metadata={"group": [qid], "task": [task]},
    )
