"""Chunked interruptible generation client with per-token version tracking.

Parity target: ``realhf/system/partial_rollout.py:29``
(PartialRolloutManager): split each generation into chunks so weight
updates only ever interrupt a chunk; carry accumulated tokens + logprobs
across chunks; sticky-route to the same server while the version is
unchanged; group ``group_size`` samples per prompt into one bundle with
``version_start``/``version_end`` per sample (the decoupled-loss inputs).

Failure recovery (docs/fault_tolerance.md): because every ``/generate``
call carries the full accumulated prefix, a dead server costs at most one
chunk — the client releases the dead route, re-``/schedule_request``s onto
a healthy server with capped exponential backoff, and the replacement
server re-prefills ``prompt + accumulated tokens`` and continues. After
``retry.max_attempts`` CONSECUTIVE failures the generation is abandoned
with :class:`GenerationAbandonedError`, which the rollout worker converts
into a clean ``/finish_rollout`` (quota never leaks, worker never dies).
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.base import logging, telemetry
from areal_tpu.base.retry import (
    DEFAULT_GENERATION_RETRY,
    FaultInjector,
    RetryPolicy,
)

logger = logging.getLogger("system.partial_rollout")


class GenerationAbandonedError(RuntimeError):
    """A chunked generation exhausted its failover retry budget."""


class NoHealthyServersError(RuntimeError):
    """The manager currently has zero routable servers (503). Transient by
    design — the health loop re-admits servers as they recover — so the
    client waits it out on its own (longer) budget rather than burning the
    millisecond-fast chunk-failover attempts."""


@dataclasses.dataclass
class GenResult:
    output_ids: List[int]
    output_logprobs: List[float]
    version_start: int
    version_end: int
    n_chunks: int


class PartialRolloutClient:
    """Async client: one ``generate`` = N chunked HTTP calls routed through
    the gserver manager."""

    def __init__(self, manager_url: str, session, chunk_tokens: int = 128,
                 retry: Optional[RetryPolicy] = None,
                 fault_injector: Optional[FaultInjector] = None,
                 no_server_wait_secs: float = 180.0,
                 request_class: str = "rollout",
                 manager_resolver=None):
        self.manager_url = manager_url
        self.session = session  # aiohttp.ClientSession
        self.chunk_tokens = chunk_tokens
        self.retry = retry or DEFAULT_GENERATION_RETRY
        # Optional () -> url callable re-resolving the manager's CURRENT
        # endpoint (name_resolve): a supervised gen-fleet respawn binds a
        # fresh port, and scheduling must follow it there instead of
        # hammering the dead incarnation's socket.
        self._manager_resolver = manager_resolver
        # Serving-engine request class (docs/serving.md): tags the
        # manager's lease and the server's admission/priority/SLO
        # decisions. "interactive"/"eval" clients share the fleet with
        # bulk rollout traffic at a higher scheduling priority.
        self.request_class = request_class
        # Whole-fleet-empty budget: must comfortably outlast an eviction +
        # re-admission cycle — detection (health interval x threshold, ~6s
        # at defaults) plus the re-admission weight reconcile, which is
        # budgeted up to fanout_retry.max_attempts x fanout_timeout_secs
        # (~120s at manager defaults).
        self.no_server_wait_secs = no_server_wait_secs
        self.faults = fault_injector
        # Failover observability (asserted by chaos tests, exported by the
        # rollout worker's status callback).
        self.n_failovers = 0
        self.n_abandoned = 0

    def _refresh_manager_url(self) -> None:
        if self._manager_resolver is None:
            return
        try:
            url = self._manager_resolver()
        except Exception:  # noqa: BLE001 — key cleared mid-respawn
            return
        if url and url != self.manager_url:
            logger.warning(f"manager endpoint moved "
                           f"{self.manager_url} -> {url}; re-routing")
            self.manager_url = url

    async def _schedule(self) -> Dict:
        if self.faults is not None:
            self.faults.maybe_fail("schedule")
        try:
            async with self.session.post(
                f"{self.manager_url}/schedule_request",
                json={"class": self.request_class},
            ) as r:
                d = await r.json()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — manager itself is down
            # An unreachable MANAGER is fleet-empty from this client's
            # perspective: burn the (long) no-server wait budget, not the
            # millisecond chunk-failover attempts, and chase the
            # re-registered endpoint before the next poll.
            self._refresh_manager_url()
            raise NoHealthyServersError(
                f"manager unreachable: {e}"
            ) from None
        if not d.get("url"):
            raise NoHealthyServersError(d.get("reason", "unknown"))
        return d

    async def _release(self, route: Dict) -> None:
        await self.session.post(
            f"{self.manager_url}/release",
            json={"lease_id": route.get("lease_id"), "url": route["url"]},
        )

    async def _release_quiet(self, route: Optional[Dict]) -> None:
        """Best-effort release of a possibly-dead route — the manager frees
        the lease/inflight slot even though the server is gone; if the
        MANAGER is also unreachable, lease TTL expiry reclaims it."""
        if route is None:
            return
        try:
            await self._release(route)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            pass

    async def _renew(self, route: Dict) -> bool:
        """Renew the sticky route's lease; False means stickiness must be
        dropped (lease expired or the server was evicted — an evicted
        server may be alive-but-stale, so routing must go back through the
        manager)."""
        lid = route.get("lease_id")
        if lid is None:
            return True  # no lease bookkeeping on this route
        async with self.session.post(f"{self.manager_url}/renew",
                                     json={"lease_id": lid}) as r:
            return bool((await r.json()).get("ok"))

    async def generate_one(
        self,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        eos_token_id: int = 1,
    ) -> GenResult:
        with telemetry.span("rollout/generate") as span_attrs:
            res = await self._generate_one(prompt_ids, gconfig, eos_token_id)
            span_attrs["n_chunks"] = res.n_chunks
            span_attrs["n_tokens"] = len(res.output_ids)
            span_attrs["versions"] = [res.version_start, res.version_end]
        return res

    async def _generate_one(
        self,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        eos_token_id: int = 1,
    ) -> GenResult:
        acc_ids: List[int] = []
        acc_lps: List[float] = []
        version_start: Optional[int] = None
        version_end = 0
        n_chunks = 0
        # The lease is held for the whole sticky lifetime (not just the
        # first chunk) so the manager's least_requests accounting sees the
        # server as busy; renewed each chunk, released on route drop/end.
        route: Optional[Dict] = None
        rid = uuid.uuid4().hex  # keys the server's persistent decode state
        failures = 0  # CONSECUTIVE chunk failures; any success resets
        fleet_waited = 0.0  # time spent waiting out an empty fleet
        throttled = 0.0  # time spent backing off admission 429s
        try:
            while len(acc_ids) < gconfig.max_new_tokens:
                left = gconfig.max_new_tokens - len(acc_ids)
                try:
                    # sticky routing while version unchanged (reference :181)
                    if route is None:
                        route = await self._schedule()
                    url = route["url"]
                    body = {
                        "rid": rid,
                        "class": self.request_class,
                        "tokens_done": len(acc_ids),
                        "prompt_ids": list(prompt_ids) + acc_ids,
                        "gconfig": {
                            **dataclasses.asdict(gconfig),
                            "max_new_tokens": min(self.chunk_tokens, left),
                            "n": 1,
                        },
                        "max_tokens": min(self.chunk_tokens, left),
                        # Full remaining token budget, not just this
                        # chunk: lets admission reject an infeasible
                        # prompt+budget at chunk 1 (413) instead of
                        # decoding to the capacity ceiling and abandoning
                        # mid-flight with every accumulated token paid for.
                        "budget_total": left,
                    }
                    if self.faults is not None:
                        self.faults.maybe_fail("generate", url=url,
                                               tokens_done=len(acc_ids))
                    t_chunk = time.monotonic()
                    async with self.session.post(
                        f"{url}/generate", json=body,
                        # Trace propagation: the generation server adopts
                        # this context for its queue-wait/prefill/decode
                        # spans; {} (telemetry off / no active trace)
                        # leaves the request byte-identical.
                        headers=telemetry.inject_headers(),
                    ) as r:
                        if r.status == 429:
                            # Admission backpressure (docs/serving.md):
                            # the server's class queue is full. Honor the
                            # retry-after hint on a separate budget — a
                            # throttle is not a failure and must not burn
                            # the chunk-failover attempts.
                            d429 = await r.json()
                            ra = float(d429.get("retry_after", 0.2))
                            telemetry.inc("rollout/admission_backoff")
                            telemetry.event(
                                "rollout/backoff_429", url=url,
                                retry_after=ra,
                                tokens_done=len(acc_ids),
                            )
                            await self._release_quiet(route)
                            route = None
                            if throttled >= self.no_server_wait_secs:
                                self.n_abandoned += 1
                                telemetry.inc("rollout/abandoned")
                                raise GenerationAbandonedError(
                                    f"admission-rejected for "
                                    f"{throttled:.0f}s "
                                    f"({len(acc_ids)} tokens accumulated)"
                                )
                            # Clamp to the remaining throttle budget: the
                            # server hint is operator-set and unbounded,
                            # and one oversized Retry-After must not
                            # sleep past the no_server_wait_secs ceiling
                            # the abandonment check enforces.
                            wait = min(
                                max(ra, 0.05),
                                max(self.no_server_wait_secs - throttled,
                                    0.05),
                            )
                            throttled += wait
                            await asyncio.sleep(wait)
                            continue
                        if r.status == 413:
                            # Permanent for this request: the prefix can
                            # never fit a KV capacity bucket.
                            self.n_abandoned += 1
                            telemetry.inc("rollout/abandoned")
                            raise GenerationAbandonedError(
                                f"prompt too long for the serving "
                                f"engine's KV capacity "
                                f"({len(prompt_ids) + len(acc_ids)} tokens)"
                            )
                        if r.status != 200:
                            raise RuntimeError(
                                f"/generate status {r.status}"
                            )
                        out = await r.json()
                    telemetry.observe("rollout/chunk_secs",
                                      time.monotonic() - t_chunk)
                except asyncio.CancelledError:
                    raise
                except GenerationAbandonedError:
                    raise  # terminal (429 budget / 413): not a failover
                except NoHealthyServersError as e:
                    # Empty fleet 503s come back in milliseconds — counting
                    # them against the chunk-failover budget would abandon
                    # every rollout within ~2s of a transient whole-fleet
                    # gap. Poll on a separate, longer budget instead.
                    await self._release_quiet(route)
                    route = None
                    telemetry.inc("rollout/no_server_503")
                    if fleet_waited >= self.no_server_wait_secs:
                        self.n_abandoned += 1
                        telemetry.inc("rollout/abandoned")
                        raise GenerationAbandonedError(
                            f"no routable generation server for "
                            f"{fleet_waited:.0f}s "
                            f"({len(acc_ids)} tokens accumulated)"
                        ) from e
                    fleet_waited += self.retry.max_delay_secs
                    await asyncio.sleep(self.retry.max_delay_secs)
                    continue
                except Exception as e:  # noqa: BLE001 — failover path
                    failures += 1
                    await self._release_quiet(route)
                    route = None
                    if failures >= self.retry.max_attempts:
                        self.n_abandoned += 1
                        telemetry.inc("rollout/abandoned")
                        raise GenerationAbandonedError(
                            f"generation abandoned after {failures} "
                            f"consecutive chunk failures "
                            f"({len(acc_ids)} tokens accumulated): {e}"
                        ) from e
                    self.n_failovers += 1
                    telemetry.inc("rollout/chunk_failovers")
                    # Failover replay leaves trace evidence: the stitched
                    # timeline (and the flight ring) shows exactly when
                    # the chunk died and how many tokens the replay
                    # re-prefilled on the replacement server.
                    telemetry.event(
                        "rollout/failover", attempt=failures,
                        tokens_done=len(acc_ids), error=str(e)[:200],
                    )
                    logger.warning(
                        f"chunk failed ({e}); re-scheduling "
                        f"(attempt {failures}/{self.retry.max_attempts}, "
                        f"{len(acc_ids)} tokens resume)"
                    )
                    await asyncio.sleep(self.retry.delay(failures))
                    continue
                failures = 0
                fleet_waited = 0.0
                throttled = 0.0
                n_chunks += 1
                acc_ids += list(out["output_ids"])
                acc_lps += list(out["output_logprobs"])
                v = int(out["version"])
                if version_start is None:
                    version_start = v
                version_end = v
                if out["finished"] or not out["output_ids"]:
                    break
                sticky = False
                if v == route.get("version", v):
                    try:
                        sticky = await self._renew(route)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — manager blip
                        sticky = False
                if not sticky:
                    # Version moved, lease refused (route was evicted —
                    # possibly alive-but-stale), or the manager blipped:
                    # drop stickiness and go back through the scheduler.
                    # Must not escape the failover loop as a raw error.
                    await self._release_quiet(route)
                    route = None
        finally:
            # Best-effort: the route (or the manager) may be dead; lease
            # TTL expiry is the backstop for a lost release.
            await self._release_quiet(route)
        return GenResult(
            output_ids=acc_ids,
            output_logprobs=acc_lps,
            version_start=version_start or 0,
            version_end=version_end,
            n_chunks=n_chunks,
        )

    async def generate_group(
        self,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        group_size: int,
        eos_token_id: int = 1,
    ) -> List[GenResult]:
        # return_exceptions so every sibling generation runs to completion
        # (releasing its route) before an abandonment is surfaced — a bare
        # gather would leak the siblings as detached background tasks.
        results = await asyncio.gather(*[
            self.generate_one(prompt_ids, gconfig, eos_token_id)
            for _ in range(group_size)
        ], return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return list(results)


def trajectory_from_gen(
    qid: str,
    j: int,
    prompt_ids: np.ndarray,
    res: GenResult,
    task: str = "math",
    task_id: int = 0,
    eos_token_id: int = 1,
):
    """One flattened trajectory SequenceSample from a chunked generation
    (same key layout as algorithms.ppo.trajectories_from_gen_output)."""
    import time as _time

    from areal_tpu.api.data import SequenceSample

    gl = max(len(res.output_ids), 1)
    toks = np.concatenate([
        prompt_ids, np.asarray(res.output_ids[:gl], np.int32)
    ]) if res.output_ids else np.concatenate([prompt_ids, [eos_token_id]])
    P = len(prompt_ids)
    gl = len(toks) - P
    lps = np.concatenate([
        np.zeros(P, np.float32),
        np.asarray((res.output_logprobs + [0.0])[:gl], np.float32),
    ])
    no_eos = float(eos_token_id not in toks[P:])
    return SequenceSample.from_default(
        ids=[f"{qid}@{j}"],
        data={
            "packed_input_ids": toks.astype(np.int32),
            "prompt_mask": np.concatenate([
                np.ones(P, np.int32), np.zeros(gl, np.int32)
            ]),
            "packed_logprobs": lps,
            "seq_no_eos_mask": np.asarray([no_eos], np.float32),
            "task_ids": np.asarray([task_id], np.int32),
            "version_start": np.asarray([res.version_start], np.int32),
            "version_end": np.asarray([res.version_end], np.int32),
            "birth_time": np.asarray([_time.time()], np.float64),
        },
        seqlens=[len(toks)],
        metadata={"group": [qid], "task": [task]},
    )
