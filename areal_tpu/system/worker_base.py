"""Worker lifecycle FSM + out-of-band control channel.

Parity target: ``realhf/system/worker_base.py:474`` (Worker FSM
configure→running→paused→exiting driven by a ZMQ control socket served
between ``_poll`` iterations, ``WorkerServer`` :71, ``WorkerControlPanel``
:218) and ``realhf/system/worker_control.py:22-170``.

TPU-shape: workers here are not a class hierarchy — master/trainer/rollout
loops already exist (system/*.py) and each has a natural per-iteration
yield point. ``WorkerControl`` is an embeddable control endpoint: the
worker calls ``control.step(status_fn)`` once per loop iteration; a
``WorkerControlPanel`` (the launcher, an operator shell, a test) discovers
workers through name_resolve and sends pause / resume / exit / status /
reconfigure commands. ``pause`` BLOCKS the worker inside ``step`` until
resume/exit — the same semantics the reference uses to freeze workers
during experiment reconfiguration.
"""

from __future__ import annotations

import pickle
import time
from enum import Enum
from typing import Any, Callable, Dict, List, Optional

import zmq

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("system.worker_base")


class WorkerState(str, Enum):
    CREATED = "created"
    RUNNING = "running"
    PAUSED = "paused"
    EXITING = "exiting"


def worker_control_key(experiment: str, trial: str, worker: str) -> str:
    return f"{names.trial_root(experiment, trial)}/worker_control/{worker}"


def worker_control_root(experiment: str, trial: str) -> str:
    return f"{names.trial_root(experiment, trial)}/worker_control/"


class WorkerControl:
    """Worker-side REP endpoint, served between loop iterations."""

    def __init__(self, experiment: str, trial: str, worker_name: str):
        self.worker_name = worker_name
        self.state = WorkerState.CREATED
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        host = network.gethostip()
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self._key = worker_control_key(experiment, trial, worker_name)
        name_resolve.add(self._key, f"tcp://{host}:{port}", replace=True)
        self._reconfigure_cb: Optional[Callable[[Any], Any]] = None
        self._t_start = time.monotonic()
        self._iterations = 0

    def on_reconfigure(self, cb: Callable[[Any], Any]) -> None:
        """Register the worker's reconfigure handler (payload → result)."""
        self._reconfigure_cb = cb

    @property
    def should_exit(self) -> bool:
        return self.state == WorkerState.EXITING

    def _status(self, status_fn: Optional[Callable[[], Dict]]) -> Dict:
        d = {
            "worker": self.worker_name,
            "state": self.state.value,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "iterations": self._iterations,
        }
        if status_fn is not None:
            try:
                d.update(status_fn())
            except Exception as e:  # noqa: BLE001 — status must never kill
                d["status_error"] = str(e)
        return d

    def _handle(self, msg: Dict, status_fn) -> Dict:
        cmd = msg.get("cmd")
        if cmd == "pause":
            if self.state == WorkerState.RUNNING:
                self.state = WorkerState.PAUSED
            return {"ok": True, "state": self.state.value}
        if cmd == "resume":
            if self.state == WorkerState.PAUSED:
                self.state = WorkerState.RUNNING
            return {"ok": True, "state": self.state.value}
        if cmd == "exit":
            self.state = WorkerState.EXITING
            return {"ok": True, "state": self.state.value}
        if cmd == "status":
            return {"ok": True, **self._status(status_fn)}
        if cmd == "reconfigure":
            if self._reconfigure_cb is None:
                return {"ok": False, "error": "no reconfigure handler"}
            try:
                res = self._reconfigure_cb(msg.get("payload"))
                return {"ok": True, "result": res}
            except Exception as e:  # noqa: BLE001 — reported to the panel
                return {"ok": False, "error": str(e)}
        return {"ok": False, "error": f"unknown command {cmd!r}"}

    def step(
        self,
        status_fn: Optional[Callable[[], Dict]] = None,
        timeout_ms: int = 0,
    ) -> WorkerState:
        """Process pending control messages; BLOCK while paused. Call once
        per worker loop iteration (the reference serves its control socket
        the same way between _poll calls)."""
        if self.state == WorkerState.CREATED:
            self.state = WorkerState.RUNNING
        self._iterations += 1
        while True:
            wait = 200 if self.state == WorkerState.PAUSED else timeout_ms
            if not self._sock.poll(wait):
                if self.state == WorkerState.PAUSED:
                    continue
                return self.state
            msg = pickle.loads(self._sock.recv())
            self._sock.send(pickle.dumps(self._handle(msg, status_fn)))
            if self.state not in (WorkerState.PAUSED,):
                return self.state

    def close(self) -> None:
        # Withdraw the advertisement so a restarted experiment's panel
        # never resolves this dead endpoint (stale-address hang).
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        self._sock.close(linger=0)


class WorkerControlPanel:
    """Launcher/operator-side client: discover + command workers."""

    def __init__(self, experiment: str, trial: str, timeout: float = 10.0):
        self.experiment = experiment
        self.trial = trial
        self.timeout = timeout
        self._ctx = zmq.Context.instance()
        self._socks: Dict[str, zmq.Socket] = {}

    def list_workers(self) -> List[str]:
        root = worker_control_root(self.experiment, self.trial)
        return sorted(
            k[len(root):] for k in name_resolve.find_subtree(root)
        )

    def _sock_for(self, worker: str) -> zmq.Socket:
        if worker not in self._socks:
            addr = name_resolve.wait(
                worker_control_key(self.experiment, self.trial, worker),
                timeout=self.timeout,
            )
            s = self._ctx.socket(zmq.REQ)
            s.setsockopt(zmq.RCVTIMEO, int(self.timeout * 1000))
            s.setsockopt(zmq.SNDTIMEO, int(self.timeout * 1000))
            s.connect(addr)
            self._socks[worker] = s
        return self._socks[worker]

    def command(self, worker: str, cmd: str, **kw) -> Dict:
        s = self._sock_for(worker)
        try:
            s.send(pickle.dumps({"cmd": cmd, **kw}))
            return pickle.loads(s.recv())
        except zmq.ZMQError as e:
            # A timed-out REQ socket is stuck in its awaiting-reply state
            # (every further send raises EFSM) — tear it down so the next
            # command reconnects fresh. Workers serve control only between
            # loop iterations, so timeouts during a long step are normal.
            s.close(linger=0)
            self._socks.pop(worker, None)
            raise TimeoutError(
                f"worker {worker!r} did not answer {cmd!r} within "
                f"{self.timeout}s (busy in a step?): {e}"
            ) from None

    def pause(self, worker: str) -> Dict:
        return self.command(worker, "pause")

    def resume(self, worker: str) -> Dict:
        return self.command(worker, "resume")

    def exit(self, worker: str) -> Dict:
        return self.command(worker, "exit")

    def status(self, worker: str) -> Dict:
        return self.command(worker, "status")

    def reconfigure(self, worker: str, payload: Any) -> Dict:
        return self.command(worker, "reconfigure", payload=payload)

    def pause_all(self) -> Dict[str, Dict]:
        return {w: self.pause(w) for w in self.list_workers()}

    def resume_all(self) -> Dict[str, Dict]:
        return {w: self.resume(w) for w in self.list_workers()}

    def close(self) -> None:
        for s in self._socks.values():
            s.close(linger=0)
