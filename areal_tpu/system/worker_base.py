"""Worker lifecycle FSM + out-of-band control channel.

Parity target: ``realhf/system/worker_base.py:474`` (Worker FSM
configure→running→paused→exiting driven by a ZMQ control socket served
between ``_poll`` iterations, ``WorkerServer`` :71, ``WorkerControlPanel``
:218) and ``realhf/system/worker_control.py:22-170``.

TPU-shape: workers here are not a class hierarchy — master/trainer/rollout
loops already exist (system/*.py) and each has a natural per-iteration
yield point. ``WorkerControl`` is an embeddable control endpoint: the
worker calls ``control.step(status_fn)`` once per loop iteration; a
``WorkerControlPanel`` (the launcher, an operator shell, a test) discovers
workers through name_resolve and sends pause / resume / exit / status /
reconfigure commands. ``pause`` BLOCKS the worker inside ``step`` until
resume/exit — the same semantics the reference uses to freeze workers
during experiment reconfiguration.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
from enum import Enum
from typing import Any, Callable, Dict, Iterable, List, Optional

import zmq

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("system.worker_base")

# Set by the supervisor (system/supervisor.py) on every spawned child:
# the incarnation id distinguishes a respawned worker's registrations
# from its dead predecessor's ghosts, and the keepalive TTL puts a
# liveness lease on its name-resolve advertisements.
ENV_INCARNATION = "AREAL_WORKER_INCARNATION"
ENV_KEEPALIVE_TTL = "AREAL_WORKER_KEEPALIVE_TTL"
ENV_HEARTBEAT_INTERVAL = "AREAL_WORKER_HEARTBEAT_INTERVAL"


def env_incarnation() -> int:
    try:
        return int(os.environ.get(ENV_INCARNATION, "0"))
    except ValueError:
        return 0


def _env_positive_float(name: str) -> Optional[float]:
    try:
        v = float(os.environ.get(name, "0"))
    except ValueError:
        return None
    return v if v > 0 else None


def env_keepalive_ttl() -> Optional[float]:
    return _env_positive_float(ENV_KEEPALIVE_TTL)


def env_heartbeat_interval() -> Optional[float]:
    return _env_positive_float(ENV_HEARTBEAT_INTERVAL)


def default_heartbeat_interval(ttl: float) -> float:
    """The heartbeat cadence for a lease of ``ttl`` seconds: explicit
    operator override (fault_tolerance.heartbeat_interval_secs via the
    supervisor's env stamp) or ttl/3."""
    return env_heartbeat_interval() or ttl / 3.0


def read_heartbeats(experiment: str, trial: str) -> Dict[str, Dict]:
    """Heartbeat AGE of every worker publishing one: worker ->
    {age_secs, incarnation, pid}. The single reader all observers share
    (panel, supervisor gauges, perf_probe fleet-status) — the record
    format lives in exactly one writer (_beat) and one parser (here)."""
    root = names.worker_heartbeat_root(experiment, trial)
    out: Dict[str, Dict] = {}
    now = time.time()
    for key in name_resolve.find_subtree(root):
        worker = key[len(root.rstrip("/")) + 1:]
        try:
            d = json.loads(name_resolve.get(key))
            out[worker] = {
                "age_secs": round(now - float(d.get("ts", 0.0)), 3),
                "incarnation": int(d.get("incarnation", 0)),
                "pid": d.get("pid"),
            }
        except Exception:  # noqa: BLE001 — torn write / stale format
            out[worker] = {"age_secs": None}
    return out


class WorkerState(str, Enum):
    CREATED = "created"
    RUNNING = "running"
    PAUSED = "paused"
    EXITING = "exiting"


def worker_control_key(experiment: str, trial: str, worker: str) -> str:
    return f"{names.trial_root(experiment, trial)}/worker_control/{worker}"


def worker_control_root(experiment: str, trial: str) -> str:
    return f"{names.trial_root(experiment, trial)}/worker_control/"


class HeartbeatThread:
    """Liveness heartbeat, independent of the worker's loop cadence.

    A dedicated daemon thread (NOT the control-serving loop: a worker
    blocked in a long jit compile or a paused FSM must still look alive —
    the lease exists to catch SIGKILLed processes, which take their
    threads with them) that every ``interval`` seconds:

     - ``touch``es each leased name-resolve key so its ``keepalive_ttl``
       never lapses while the process lives, and
     - rewrites ``names.worker_heartbeat`` with {ts, incarnation, pid} so
       observers (supervisor, perf_probe fleet-status) can report
       heartbeat age and tell a respawn from its predecessor's ghost.
    """

    def __init__(self, experiment: str, trial: str, worker_name: str,
                 keys: Iterable[str] = (), interval: float = 2.0,
                 incarnation: Optional[int] = None,
                 inflight_fn: Optional[Callable[[], bool]] = None):
        self.worker_name = worker_name
        self.incarnation = (
            incarnation if incarnation is not None else env_incarnation()
        )
        # key -> (value, ttl) | None. With the value recorded, a LAPSED
        # lease (stop-the-world pause, NFS stall, suspend gap longer than
        # the TTL) is RE-REGISTERED instead of being lost forever — a
        # live worker must never stay deregistered because one heartbeat
        # was late.
        self._keys: Dict[str, Optional[tuple]] = {k: None for k in keys}
        self._interval = max(float(interval), 0.05)
        self._lock = threading.Lock()
        self._hb_key = names.worker_heartbeat(experiment, trial, worker_name)
        # Compile-aware liveness (base/compile_watch.py): while
        # ``inflight_fn`` reports a jit compile in progress, publish
        # names.compile_inflight with a fresh ts every beat so the
        # sentinel can tell "compiling" from "wedged"; delete it the
        # beat the compile drains. Zero name-resolve traffic when the
        # worker never compiles (or the observatory is disabled —
        # inflight_fn is then compile_watch.NULL.inflight ≡ False).
        self._inflight_fn = inflight_fn
        self._inflight_key = names.compile_inflight(
            experiment, trial, worker_name
        )
        self._inflight_written = False
        self._stop = threading.Event()
        self._beat()  # visible before the first interval elapses
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"heartbeat-{worker_name}",
        )
        self._thread.start()

    def lease(self, key: str, value: Optional[str] = None,
              ttl: Optional[float] = None) -> None:
        """Add a name-resolve key to the touch set. With ``value`` (and
        optionally ``ttl``) recorded, an expired lease is re-registered
        on the next beat; without it the key is touch-only (the owner
        must re-add on expiry)."""
        with self._lock:
            self._keys[key] = (value, ttl) if value is not None else None

    def _beat(self) -> None:
        with self._lock:
            keys = dict(self._keys)
        for k, reg in keys.items():
            try:
                name_resolve.touch(k)
            except name_resolve.NameEntryNotFoundError:
                if reg is None:
                    continue  # touch-only key: the owner re-registers
                value, ttl = reg
                try:
                    name_resolve.add(k, value, replace=True,
                                     keepalive_ttl=ttl)
                    logger.warning(
                        f"lease on {k} had lapsed (late heartbeat?); "
                        f"re-registered"
                    )
                except Exception:  # noqa: BLE001 — retried next beat
                    pass
            except Exception:  # noqa: BLE001 — a heartbeat must never
                pass  # kill a worker
        try:
            name_resolve.add(
                self._hb_key,
                json.dumps({"ts": time.time(),
                            "incarnation": self.incarnation,
                            "pid": os.getpid()}),
                replace=True, delete_on_exit=False,
            )
        except Exception:  # noqa: BLE001
            pass
        if self._inflight_fn is None:
            return
        try:
            if self._inflight_fn():
                # Rewritten every beat: observers judge freshness by ts,
                # so a SIGKILLed worker's stale flag stops suppressing
                # alerts within ~a minute instead of forever.
                name_resolve.add(
                    self._inflight_key,
                    json.dumps({"ts": time.time()}),
                    replace=True, delete_on_exit=False,
                )
                self._inflight_written = True
            elif self._inflight_written:
                self._inflight_written = False
                name_resolve.delete(self._inflight_key)
        except Exception:  # noqa: BLE001 — a heartbeat must never
            pass  # kill a worker

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self._beat()

    def close(self) -> None:
        self._stop.set()
        # Join BEFORE deleting: an in-flight _beat() re-adding the key
        # after the delete would leave a permanent ghost heartbeat (the
        # key carries no TTL) that reads as a wedged worker forever.
        self._thread.join(timeout=2.0)
        try:
            name_resolve.delete(self._hb_key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        if self._inflight_written:
            self._inflight_written = False
            try:
                name_resolve.delete(self._inflight_key)
            except Exception:  # noqa: BLE001
                pass


class WorkerControl:
    """Worker-side REP endpoint, served between loop iterations."""

    def __init__(self, experiment: str, trial: str, worker_name: str,
                 keepalive_ttl: Optional[float] = None,
                 heartbeat_interval: Optional[float] = None,
                 inflight_fn: Optional[Callable[[], bool]] = None):
        self.worker_name = worker_name
        self.state = WorkerState.CREATED
        self.incarnation = env_incarnation()
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        host = network.gethostip()
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self._key = worker_control_key(experiment, trial, worker_name)
        # Liveness lease (docs/fault_tolerance.md): under a supervisor the
        # advertisement expires unless heartbeaten, so a SIGKILLed
        # worker's ghost endpoint vanishes from panel discovery instead
        # of hanging every later command against it.
        if keepalive_ttl is None:
            keepalive_ttl = env_keepalive_ttl()
        self._keepalive_ttl = keepalive_ttl
        addr = f"tcp://{host}:{port}"
        name_resolve.add(self._key, addr, replace=True,
                         keepalive_ttl=keepalive_ttl)
        self._hb: Optional[HeartbeatThread] = None
        if keepalive_ttl:
            self._hb = HeartbeatThread(
                experiment, trial, worker_name,
                interval=(heartbeat_interval or env_heartbeat_interval()
                          or keepalive_ttl / 3.0),
                incarnation=self.incarnation,
                inflight_fn=inflight_fn,
            )
            self._hb.lease(self._key, addr, keepalive_ttl)
        self._reconfigure_cb: Optional[Callable[[Any], Any]] = None
        self._commands: Dict[str, Callable[[Any], Any]] = {}
        self._t_start = time.monotonic()
        self._iterations = 0

    def on_reconfigure(self, cb: Callable[[Any], Any]) -> None:
        """Register the worker's reconfigure handler (payload → result)."""
        self._reconfigure_cb = cb

    def on_command(self, name: str, cb: Callable[[Any], Any]) -> None:
        """Register a custom control command (payload → result), served
        like pause/resume from within ``step`` — including while PAUSED.
        The master registers ``checkpoint`` this way so a graceful drain
        can dump a recover checkpoint out-of-band of the ckpt cadence."""
        self._commands[name] = cb

    def lease(self, key: str, value: Optional[str] = None,
              ttl: Optional[float] = None) -> None:
        """Keep an additional name-resolve key alive on this worker's
        heartbeat (e.g. the trainer's request-stream advertisement);
        with ``value`` given, a lapsed lease is re-registered. No-op
        without a keepalive lease."""
        if self._hb is not None:
            self._hb.lease(key, value, ttl or self._keepalive_ttl)

    @property
    def should_exit(self) -> bool:
        return self.state == WorkerState.EXITING

    def _status(self, status_fn: Optional[Callable[[], Dict]]) -> Dict:
        d = {
            "worker": self.worker_name,
            "state": self.state.value,
            "incarnation": self.incarnation,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "iterations": self._iterations,
        }
        if status_fn is not None:
            try:
                d.update(status_fn())
            except Exception as e:  # noqa: BLE001 — status must never kill
                d["status_error"] = str(e)
        return d

    def _handle(self, msg: Dict, status_fn) -> Dict:
        cmd = msg.get("cmd")
        if cmd == "pause":
            if self.state == WorkerState.RUNNING:
                self.state = WorkerState.PAUSED
            return {"ok": True, "state": self.state.value}
        if cmd == "resume":
            if self.state == WorkerState.PAUSED:
                self.state = WorkerState.RUNNING
            return {"ok": True, "state": self.state.value}
        if cmd == "exit":
            self.state = WorkerState.EXITING
            return {"ok": True, "state": self.state.value}
        if cmd == "status":
            return {"ok": True, **self._status(status_fn)}
        if cmd == "reconfigure":
            if self._reconfigure_cb is None:
                return {"ok": False, "error": "no reconfigure handler"}
            try:
                res = self._reconfigure_cb(msg.get("payload"))
                return {"ok": True, "result": res}
            except Exception as e:  # noqa: BLE001 — reported to the panel
                return {"ok": False, "error": str(e)}
        if cmd in self._commands:
            try:
                res = self._commands[cmd](msg.get("payload"))
                return {"ok": True, "result": res}
            except Exception as e:  # noqa: BLE001 — reported to the panel
                return {"ok": False, "error": str(e)}
        return {"ok": False, "error": f"unknown command {cmd!r}"}

    def step(
        self,
        status_fn: Optional[Callable[[], Dict]] = None,
        timeout_ms: int = 0,
    ) -> WorkerState:
        """Process pending control messages; BLOCK while paused. Call once
        per worker loop iteration (the reference serves its control socket
        the same way between _poll calls)."""
        if self.state == WorkerState.CREATED:
            self.state = WorkerState.RUNNING
        self._iterations += 1
        while True:
            wait = 200 if self.state == WorkerState.PAUSED else timeout_ms
            if not self._sock.poll(wait):
                if self.state == WorkerState.PAUSED:
                    continue
                return self.state
            msg = pickle.loads(self._sock.recv())
            self._sock.send(pickle.dumps(self._handle(msg, status_fn)))
            if self.state not in (WorkerState.PAUSED,):
                return self.state

    def close(self) -> None:
        if self._hb is not None:
            self._hb.close()
        # Withdraw the advertisement so a restarted experiment's panel
        # never resolves this dead endpoint (stale-address hang).
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        self._sock.close(linger=0)


class WorkerControlPanel:
    """Launcher/operator-side client: discover + command workers."""

    def __init__(self, experiment: str, trial: str, timeout: float = 10.0):
        self.experiment = experiment
        self.trial = trial
        self.timeout = timeout
        self._ctx = zmq.Context.instance()
        self._socks: Dict[str, zmq.Socket] = {}

    def list_workers(self) -> List[str]:
        root = worker_control_root(self.experiment, self.trial)
        return sorted(
            k[len(root):] for k in name_resolve.find_subtree(root)
        )

    def _sock_for(self, worker: str) -> zmq.Socket:
        if worker not in self._socks:
            addr = name_resolve.wait(
                worker_control_key(self.experiment, self.trial, worker),
                timeout=self.timeout,
            )
            s = self._ctx.socket(zmq.REQ)
            s.setsockopt(zmq.RCVTIMEO, int(self.timeout * 1000))
            s.setsockopt(zmq.SNDTIMEO, int(self.timeout * 1000))
            s.connect(addr)
            self._socks[worker] = s
        return self._socks[worker]

    def command(self, worker: str, cmd: str, **kw) -> Dict:
        s = self._sock_for(worker)
        try:
            s.send(pickle.dumps({"cmd": cmd, **kw}))
            return pickle.loads(s.recv())
        except zmq.ZMQError as e:
            # A timed-out REQ socket is stuck in its awaiting-reply state
            # (every further send raises EFSM) — tear it down so the next
            # command reconnects fresh. Workers serve control only between
            # loop iterations, so timeouts during a long step are normal.
            s.close(linger=0)
            self._socks.pop(worker, None)
            raise TimeoutError(
                f"worker {worker!r} did not answer {cmd!r} within "
                f"{self.timeout}s (busy in a step?): {e}"
            ) from None

    def pause(self, worker: str) -> Dict:
        return self.command(worker, "pause")

    def resume(self, worker: str) -> Dict:
        return self.command(worker, "resume")

    def exit(self, worker: str) -> Dict:
        return self.command(worker, "exit")

    def status(self, worker: str) -> Dict:
        return self.command(worker, "status")

    def reconfigure(self, worker: str, payload: Any) -> Dict:
        return self.command(worker, "reconfigure", payload=payload)

    def try_command(self, worker: str, cmd: str, **kw) -> Dict:
        """``command`` that reports a timeout instead of raising — drain
        sequences keep going past one unresponsive worker."""
        try:
            return self.command(worker, cmd, **kw)
        except TimeoutError as e:
            return {"ok": False, "error": str(e)}

    def pause_all(self) -> Dict[str, Dict]:
        return {w: self.pause(w) for w in self.list_workers()}

    def resume_all(self) -> Dict[str, Dict]:
        return {w: self.resume(w) for w in self.list_workers()}

    def exit_all(self) -> Dict[str, Dict]:
        return {w: self.try_command(w, "exit")
                for w in self.list_workers()}

    def heartbeats(self) -> Dict[str, Dict]:
        """Heartbeat AGE of every worker publishing one: worker ->
        {age_secs, incarnation, pid}. A large age with a live process
        means a wedged worker; a missing entry means no heartbeat was
        ever configured (no supervisor / leases disabled)."""
        return read_heartbeats(self.experiment, self.trial)

    def close(self) -> None:
        for s in self._socks.values():
            s.close(linger=0)
