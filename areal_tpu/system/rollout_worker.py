"""Rollout worker — CPU async driver of agents against the generation fleet.

Parity target: ``realhf/system/rollout_worker.py:43``: owns a dataset
shard; for each prompt asks the gserver manager for rollout quota
(``/allocate_rollout`` — the staleness gate), runs
``agent.collect_trajectory`` with obs/act queues bridged to the chunked
generation client (partial_rollout.py), pushes accepted trajectories to the
trainer over the ZMQ push stream, and reports ``/finish_rollout``.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import time
from typing import Any, Dict, Optional

import numpy as np

import areal_tpu.agents  # noqa: F401 — registers built-in agents/envs
from areal_tpu.api.data import SequenceSample
from areal_tpu.api.model import GenerationHyperparameters, make_agent
from areal_tpu.api.train_config import (
    DurabilityConfig,
    GoodputConfig,
    RewardServiceConfig,
    TelemetryConfig,
)
from areal_tpu.base import logging, name_resolve, names, telemetry
from areal_tpu.system import goodput as goodput_mod
from areal_tpu.rewards import client as reward_client
from areal_tpu.datasets.jsonl import RL_TASKS, load_jsonl, load_shuffle_split
from areal_tpu.base.retry import (
    DEFAULT_GENERATION_RETRY,
    FaultInjector,
    RetryPolicy,
)
from areal_tpu.system.partial_rollout import (
    GenerationAbandonedError,
    PartialRolloutClient,
    trajectory_from_gen,
)
from areal_tpu.system.sample_spool import (
    SampleSpool,
    SpoolSender,
    ack_channel_name,
)
from areal_tpu.system.streams import ZmqPuller, ZmqPusher

logger = logging.getLogger("system.rollout")


@dataclasses.dataclass
class RolloutWorkerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    worker_index: int = 0
    n_workers: int = 1
    dataset_path: str = ""
    trainer_handler: str = "trainer"  # puller name to push to
    agent: str = "math_single_step"
    agent_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Reward environment (api/model env registry). The default grades
    # math AND code by task kind; code-RL workloads can pick
    # "code_single_step" (format gate + optional pass-rate credit).
    env: str = "math_code_single_step"
    env_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    group_size: int = 1
    chunk_tokens: int = 128
    max_concurrent: int = 8
    eos_token_id: int = 1
    seed: int = 1
    tokenizer: Any = None
    max_rollouts: Optional[int] = None  # stop after N (tests); None = forever
    # Async-mode recovery: consumed prompt uids are appended to
    # {recover_dir}/rollout_consumed_{index}.log; a restarted worker skips
    # them so recovered runs don't re-train the same prompts (reference
    # rollout_worker.py:180-184 hash_vals_to_ignore skiplist).
    recover_dir: str = ""
    # Chunk-failover policy (docs/fault_tolerance.md): a failed /generate
    # chunk re-schedules onto a healthy server with capped exponential
    # backoff; after max_attempts CONSECUTIVE failures the rollout is
    # abandoned (clean /finish_rollout, worker stays alive).
    retry: RetryPolicy = dataclasses.field(
        default_factory=lambda: DEFAULT_GENERATION_RETRY
    )
    # Unified telemetry (base/telemetry.py): per-generation lifecycle
    # spans, chunk-latency histograms, staleness lag. Off by default.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Goodput ledger (system/goodput.py): gate-wait / generation-wait /
    # grading counters in ACCRUAL mode — N concurrent rollouts make a
    # wall-clock partition meaningless, so this worker exports
    # task-seconds (excluded from fleet chip goodput). Off by default.
    goodput: GoodputConfig = dataclasses.field(default_factory=GoodputConfig)
    # Sandbox reward fleet (docs/rewards.md): enabled, agent reward
    # callbacks fan grading out to the reward workers instead of
    # executing verification in THIS process. Off = legacy local grading.
    reward_service: RewardServiceConfig = dataclasses.field(
        default_factory=RewardServiceConfig
    )
    # Durable sample delivery (system/sample_spool.py): enabled, every
    # accepted trajectory is fsynced to {recover_dir}/spool_{index}/
    # BEFORE the prompt enters the ConsumedLog, and a background sender
    # owns the push socket (acks, replay, resend). Off = the legacy
    # fire-and-forget push, bit-identical wire bytes.
    durability: DurabilityConfig = dataclasses.field(
        default_factory=DurabilityConfig
    )


class ConsumedLog:
    """Append-only consumed-uid log for async recovery. One file per
    rollout worker.

    Durability is the whole point of this file — a record that did not
    reach disk before a crash means a recovered run RE-TRAINS that
    prompt, the exact bug the log exists to prevent. So every append is
    flushed AND fsynced before ``add`` returns (records are tiny; the
    fsync is amortized by the network round-trips that precede it), and
    the reader tolerates a torn tail: a final line without its
    terminating newline is a record whose write was cut mid-append — it
    never fully landed, so it is dropped (that prompt re-trains once,
    which is the safe direction)."""

    def __init__(self, recover_dir: str, worker_index: int):
        self.path = (
            os.path.join(recover_dir, f"rollout_consumed_{worker_index}.log")
            if recover_dir else None
        )
        self.seen = set()
        self._fh = None
        if self.path and os.path.exists(self.path):
            with open(self.path, "rb") as f:
                raw = f.read()
            data = raw.decode(errors="replace")
            lines = data.split("\n")
            if data and not data.endswith("\n"):
                torn = lines.pop()
                logger.warning(
                    f"consumed log {self.path}: dropping torn tail "
                    f"{torn[:64]!r} (crash mid-append); the prompt will "
                    f"be re-trained"
                )
                # Repair in place: truncating the fragment keeps later
                # appends from merging into it (which would corrupt the
                # NEXT record too).
                with open(self.path, "rb+") as f:
                    f.truncate(raw.rfind(b"\n") + 1)
            self.seen = {ln.strip() for ln in lines if ln.strip()}

    def __contains__(self, uid: str) -> bool:
        return uid in self.seen

    def add(self, uid: str) -> None:
        if uid in self.seen:
            return
        self.seen.add(uid)
        if self.path:
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(uid + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class RolloutWorker:
    def __init__(self, cfg: RolloutWorkerConfig,
                 fault_injector: Optional[FaultInjector] = None):
        self.cfg = cfg
        self.faults = fault_injector
        records = load_jsonl(cfg.dataset_path)
        self.records = load_shuffle_split(
            records, cfg.seed, cfg.worker_index, cfg.n_workers
        )
        self.id2info = {str(d["query_id"]): d for d in self.records}
        self.agent = make_agent(
            cfg.agent, tokenizer=cfg.tokenizer, **cfg.agent_args
        )
        from areal_tpu.api.model import make_env

        self.env = make_env(cfg.env, self.id2info, **cfg.env_args)
        # Reward grading mode for THIS worker process (rewards/client.py):
        # with the service enabled, agent callbacks fan grading out to the
        # sandbox fleet — zero in-rollout-process code execution.
        reward_client.configure_service(
            cfg.reward_service, cfg.experiment, cfg.trial
        )
        self.consumed = ConsumedLog(cfg.recover_dir, cfg.worker_index)
        self._mgr_url0 = ""  # pre-client bootstrap; see _mgr_url property
        self._done = 0
        self._pushed = 0
        self._abandoned = 0
        self._sender: Optional[SpoolSender] = None  # armed by run_async
        # Goodput accounting (null until run_async arms it).
        self._ledger = goodput_mod.NULL_LEDGER

    def _prompt_sample(self, rec, uid: str) -> SequenceSample:
        ids = self.cfg.tokenizer.encode(rec["prompt"])
        return SequenceSample.from_default(
            ids=[uid],
            data={"packed_prompts": np.asarray(ids, np.int32)},
            seqlens=[len(ids)],
            metadata={"task": [rec.get("task", "math")]},
        )

    @staticmethod
    async def _post_json(session, url: str, payload: Dict,
                         timeout_secs: float = 15.0) -> Dict:
        # Explicit bound: quota RPCs run inside cancellation shields, so a
        # hung manager must not pin worker shutdown on aiohttp's 300s
        # default total timeout.
        import aiohttp

        async with session.post(
            url, json=payload,
            timeout=aiohttp.ClientTimeout(total=timeout_secs),
            # Trace propagation (docs/observability.md): the active
            # sample trace rides /allocate_rollout and /finish_rollout;
            # empty dict (telemetry off / no trace) leaves the request
            # byte-identical.
            headers=telemetry.inject_headers(),
        ) as r:
            return await r.json()

    @property
    def _mgr_url(self) -> str:
        """The manager's endpoint — owned by the PartialRolloutClient
        once it exists (ONE source of truth: the client's resolver and
        this worker's quota RPCs must never diverge onto different
        incarnations of a respawned manager)."""
        client = getattr(self, "client", None)
        return client.manager_url if client is not None else self._mgr_url0

    def _refresh_mgr_url(self) -> None:
        """Re-resolve the gserver manager's endpoint: a supervised
        gen-fleet respawn binds a fresh port and re-registers under the
        same name_resolve key — the worker must follow it there instead
        of retrying the dead incarnation's socket forever."""
        client = getattr(self, "client", None)
        if client is not None:
            client._refresh_manager_url()

    async def _rollout_one(self, rec, uid, client, pusher, session):
        cfg = self.cfg
        # quota / staleness gate — allocate in SAMPLE units: one prompt
        # produces group_size samples, and the manager's is_staled /
        # max_concurrent_rollouts bookkeeping counts samples (reference
        # gserver_manager.py:351 compares against train_batch_size samples).
        #
        # The allocation RPC must be cancellation-ATOMIC: if this task is
        # cancelled after the manager booked quota but before our
        # try/finally owns it, running_rollouts would leak forever. Shield
        # the RPC, and on cancellation let it complete and compensate.
        t_alloc = time.monotonic()
        alloc_fut = asyncio.ensure_future(self._post_json(
            session, f"{self._mgr_url}/allocate_rollout",
            {"n_samples": cfg.group_size},
        ))
        try:
            alloc = await asyncio.shield(alloc_fut)
        except asyncio.CancelledError:
            try:
                alloc = await alloc_fut
            except Exception:  # noqa: BLE001 — RPC itself failed: no booking
                alloc = None
            if alloc is not None and alloc.get("allowed"):
                try:
                    await self._post_json(
                        session, f"{self._mgr_url}/finish_rollout",
                        {"accepted": False, "n_samples": cfg.group_size,
                         "n_accepted": 0},
                    )
                except Exception as e2:  # noqa: BLE001 — manager hung/dead
                    logger.warning(
                        f"compensating finish_rollout failed ({e2}); "
                        f"{cfg.group_size} samples of quota may leak until "
                        f"the manager restarts"
                    )
            raise
        except Exception as e:  # noqa: BLE001 — manager blip: not fatal
            # A failed allocation made no booking — retry later instead of
            # letting the error reach d.result() and kill the worker (the
            # same survival contract the /generate chunks have). The
            # manager may have been respawned at a new port: re-resolve
            # before the retry.
            logger.warning(f"allocate_rollout failed ({e}); retrying")
            self._refresh_mgr_url()
            await asyncio.sleep(1.0)
            return "retry"
        if not alloc.get("allowed"):
            telemetry.inc("rollout/alloc_denied")
            telemetry.inc(
                f"rollout/alloc_denied_{alloc.get('reason', 'unknown')}"
            )
            # Overload backpressure (docs/fault_tolerance.md
            # §Autoscaling): when the fleet is pinned at its max bound
            # and saturated, the manager's denial carries a Retry-After
            # hint — slow prompt admission to its cadence instead of
            # re-polling the gate every 0.5s from every pending prompt.
            retry_secs = 0.5
            if alloc.get("retry_after") is not None:
                try:
                    retry_secs = max(float(alloc["retry_after"]), 0.05)
                except (TypeError, ValueError):
                    pass
                else:
                    telemetry.inc("rollout/backpressure_throttled")
            await asyncio.sleep(retry_secs)
            return "retry"
        telemetry.observe("rollout/alloc_rpc_secs",
                          time.monotonic() - t_alloc)
        accepted = 0
        abandoned = False
        task = None
        try:
            prompt = self._prompt_sample(rec, uid)
            obs_q: asyncio.Queue = asyncio.Queue()
            act_q: asyncio.Queue = asyncio.Queue()
            task = asyncio.create_task(
                self.agent.collect_trajectory(prompt, self.env, obs_q, act_q)
            )
            rec_task = rec.get("task", "math")
            # Service the agent's obs→act exchanges until it returns: one
            # round for single-step agents, num_turns rounds for multi-turn
            # (reference rollout_worker.py:330 rollout_task loops the same
            # way via PartialRolloutManager).
            turn = 0
            while True:
                get_obs = asyncio.create_task(obs_q.get())
                done, _ = await asyncio.wait(
                    {task, get_obs}, return_when=asyncio.FIRST_COMPLETED
                )
                if get_obs not in done:
                    get_obs.cancel()
                    break
                qid, prompt_ids, gconfig = get_obs.result()
                gconfig = gconfig or cfg.gconfig
                t_gen = time.monotonic()
                results = await client.generate_group(
                    list(map(int, prompt_ids)), gconfig,
                    gconfig.n if gconfig is not cfg.gconfig else cfg.group_size,
                    eos_token_id=cfg.eos_token_id,
                )
                # Goodput: generation-wait — time this rollout spent
                # blocked on the fleet (comm from the driver's seat).
                self._ledger.add("comm", time.monotonic() - t_gen)
                trajs = [
                    trajectory_from_gen(
                        f"{qid}@t{turn}" if turn else qid, j,
                        np.asarray(prompt_ids, np.int32), res,
                        task=rec_task, task_id=RL_TASKS.index(rec_task),
                        eos_token_id=cfg.eos_token_id,
                    )
                    for j, res in enumerate(results)
                ]
                turn += 1
                await act_q.put(trajs)
            t_grade = time.monotonic()
            final = await task
            # Goodput: grading/finalization — the agent's reward path
            # (env.step fanout or local grading) after the last chunk.
            self._ledger.add("compute", time.monotonic() - t_grade)
            for t in final:
                payload = t.as_json_compatible()
                if self._sender is not None:
                    # Durable path: fsynced into the spool (off the event
                    # loop — the append blocks on disk, and on spool
                    # backpressure) BEFORE ``one()`` marks the prompt
                    # consumed; the sender thread owns the actual push.
                    await asyncio.to_thread(self._sender.submit, payload)
                else:
                    pusher.push(payload)
                if "version_start" in t.data:
                    # Version-staleness lag at submit: how many weight
                    # versions elapsed while this trajectory generated —
                    # the decoupled-loss off-policyness the staleness gate
                    # is supposed to bound.
                    lag = float(np.asarray(t.data["version_end"])[0]
                                - np.asarray(t.data["version_start"])[0])
                    telemetry.observe(
                        "rollout/staleness_lag", lag,
                        buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0),
                    )
                    # Last-value gauge alongside the histogram: the
                    # sentinel evaluates scalar series, and a cumulative
                    # histogram has no "current" reading (distinct name —
                    # one Prometheus family cannot be both kinds).
                    telemetry.set_gauge("rollout/staleness_current", lag)
            accepted = len(final)
            self._pushed += accepted
            telemetry.inc("rollout/trajectories_pushed", accepted)
        except GenerationAbandonedError as e:
            # The generation fleet stayed dead through the whole failover
            # budget. Abandon THIS rollout cleanly — the finally below
            # reports /finish_rollout with the exact allocation so
            # running_rollouts drains to 0 — and keep the worker alive.
            self._abandoned += 1
            abandoned = True
            logger.warning(f"rollout {uid} abandoned: {e}")
        finally:
            # Release EXACTLY what was allocated (group_size samples) so the
            # manager's running_rollouts never drifts; acceptance only gates
            # how many samples count as headed for the trainer (n_accepted).
            # Shielded like the allocation: a cancellation arriving during
            # cleanup must not skip the /finish_rollout report.
            async def _cleanup():
                if task is not None and not task.done():
                    task.cancel()
                    await asyncio.gather(task, return_exceptions=True)
                try:
                    await self._post_json(
                        session, f"{self._mgr_url}/finish_rollout",
                        {"accepted": accepted > 0,
                         "n_samples": cfg.group_size,
                         "n_accepted": accepted},
                    )
                except Exception as e:  # noqa: BLE001 — manager hung/dead
                    logger.warning(
                        f"finish_rollout failed ({e}); {cfg.group_size} "
                        f"samples of quota may leak until the manager "
                        f"restarts"
                    )

            cleanup = asyncio.ensure_future(_cleanup())
            try:
                await asyncio.shield(cleanup)
            except asyncio.CancelledError:
                await cleanup
                raise
        self._done += 1
        # "abandoned" counts toward done (bounds test loops) but must NOT
        # mark the prompt consumed: a transient fleet outage would otherwise
        # permanently delete prompts from training (the ConsumedLog skiplist
        # persists across recovery).
        return "abandoned" if abandoned else "ok"

    async def run_async(self) -> None:
        import aiohttp

        from areal_tpu.system.worker_base import WorkerControl

        cfg = self.cfg
        if cfg.telemetry.enabled:
            telemetry.configure(
                cfg.experiment, cfg.trial, "rollout", cfg.worker_index,
                cfg.telemetry,
            )
            # Accrual-only ledger (initial_state=None): concurrent
            # rollouts export task-seconds per phase, not a wall
            # partition (module docstring in system/goodput.py).
            self._ledger = goodput_mod.make_ledger(
                cfg.goodput, telemetry.get(), initial_state=None,
            )
        ctrl = WorkerControl(
            cfg.experiment, cfg.trial, f"rollout{cfg.worker_index}"
        )
        self._mgr_url0 = name_resolve.wait(
            names.gen_server_manager(cfg.experiment, cfg.trial), timeout=300
        )
        pusher = ZmqPusher(
            cfg.experiment, cfg.trial, cfg.trainer_handler,
            block_secs=cfg.durability.push_block_secs,
        )
        ack_puller = None
        if cfg.durability.enabled:
            if not cfg.recover_dir:
                raise ValueError(
                    "durability.enabled=true needs a recover_dir: the "
                    "spool must land next to the consumed-uid log so a "
                    "respawned worker can replay it"
                )
            spool = SampleSpool(
                os.path.join(cfg.recover_dir, f"spool_{cfg.worker_index}"),
                segment_bytes=cfg.durability.spool_segment_bytes,
                max_bytes=cfg.durability.spool_max_bytes,
            )
            # Ack channel: this worker binds its own PULL socket; the
            # trainer discovers it by worker index and pushes settled
            # seqnos back. Leased on the control heartbeat like every
            # other advertisement (a SIGKILLed worker's key expires).
            ack_puller = ZmqPuller(
                cfg.experiment, cfg.trial, ack_channel_name(cfg.worker_index)
            )
            ctrl.lease(ack_puller._key, ack_puller._addr)
            self._sender = SpoolSender(
                spool, pusher, ack_puller, cfg.worker_index,
                resend_timeout_secs=cfg.durability.resend_timeout_secs,
            )
            self._sender.start()
        async with aiohttp.ClientSession() as session:
            # Reward fanout rides this worker's long-lived session
            # (keepalive reuse across grade batches); the async-with
            # owns its lifetime — the client never closes it.
            svc = reward_client.service_client()
            if svc is not None:
                svc.use_session(session)
            client = PartialRolloutClient(
                self._mgr_url, session, chunk_tokens=cfg.chunk_tokens,
                retry=cfg.retry, fault_injector=self.faults,
                # A respawned manager registers a fresh URL under the
                # same key; the client re-resolves on manager-connection
                # failures instead of wedging on the dead socket.
                manager_resolver=lambda: name_resolve.get(
                    names.gen_server_manager(cfg.experiment, cfg.trial)
                ),
            )
            self.client = client  # exposed for tests/telemetry
            sem = asyncio.Semaphore(cfg.max_concurrent)
            pos = 0

            async def one(rec, uid):
                async with sem:
                    # Sample-lineage trace ORIGIN: one trace per admitted
                    # prompt, carried (contextvars) through the quota RPC,
                    # every /generate chunk, the push to the trainer, and
                    # terminated by the trainer's train_sample span.
                    with telemetry.start_trace() as tctx, \
                            telemetry.span("rollout/rollout",
                                           uid=uid) as attrs:
                        if tctx is not None:
                            attrs["trace_id"] = tctx.trace_id
                        # A denied allocation (staleness/capacity gate) must
                        # not drop the prompt — retry until the gate opens.
                        t0 = time.monotonic()
                        t0_wall = time.time()
                        while True:
                            t_attempt = time.monotonic()
                            status = await self._rollout_one(
                                rec, uid, client, pusher, session
                            )
                            if status != "retry":
                                break
                        # Time blocked by the staleness/capacity gate (and
                        # manager blips) before the successful attempt.
                        telemetry.observe("rollout/alloc_wait_secs",
                                          t_attempt - t0)
                        # Goodput: gate-wait is data_wait from the
                        # trainer's perspective — prompts held back.
                        self._ledger.add("data_wait", t_attempt - t0)
                        # Same window as a trace-stage span so stitched
                        # timelines show where the gate held this sample.
                        if tctx is not None:
                            telemetry.add_span(
                                "rollout/gate", t0_wall, t_attempt - t0,
                                trace=tctx, uid=uid,
                            )
                        attrs["status"] = status
                    if status == "ok":
                        self.consumed.add(uid)

            pending = set()
            while cfg.max_rollouts is None or self._done < cfg.max_rollouts:
                # Control channel between scheduling rounds: pause stops
                # NEW rollouts from being issued (in-flight ones finish
                # when resumed); exit drains out of the loop.
                await asyncio.to_thread(
                    ctrl.step,
                    lambda: {"done": self._done, "pushed": self._pushed,
                             "abandoned": self._abandoned,
                             "failovers": client.n_failovers},
                )
                if ctrl.should_exit:
                    break
                telemetry.set_gauge("rollout/inflight", len(pending))
                telemetry.set_gauge("rollout/done", self._done)
                telemetry.set_gauge("rollout/failovers", client.n_failovers)
                self._ledger.poll()
                while len(pending) < cfg.max_concurrent:
                    rec = self.records[pos % len(self.records)]
                    # Epoch passes over a small dataset re-visit the same
                    # query_id; tag the pass so trajectory ids stay globally
                    # unique (the buffer rejects duplicate sample ids).
                    epoch = pos // len(self.records)
                    qid = str(rec["query_id"])
                    uid = qid if epoch == 0 else f"{qid}@r{epoch}"
                    pos += 1
                    if uid in self.consumed:  # recovered run: already pushed
                        continue
                    pending.add(asyncio.create_task(one(rec, uid)))
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for d in done:
                    d.result()  # surface exceptions
            # Drain on exit: cancel in-flight rollouts while the session is
            # still open so their finally blocks report /finish_rollout —
            # the manager's running_rollouts drains to 0, no leaked quota.
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
        if self._sender is not None:
            # Clean exit: give in-flight acks a bounded window to settle
            # so the spool drains; anything unacked stays on disk and
            # replays next incarnation (at-least-once, never lost).
            await asyncio.to_thread(
                self._sender.close, cfg.durability.drain_timeout_secs
            )
            ack_puller.close()
        ctrl.close()
        self.consumed.close()
        self._ledger.flush()
        telemetry.shutdown()  # final flush to the aggregator
        logger.info(
            f"rollout worker done: {self._pushed} trajectories pushed "
            f"({self._abandoned} abandoned, "
            f"{self.client.n_failovers} chunk failovers)"
        )

    def run(self) -> None:
        asyncio.run(self.run_async())
