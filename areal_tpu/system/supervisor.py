"""Launcher-level worker supervision: per-kind restart policy, crash-loop
circuit breaker, liveness accounting, and graceful preemption drain.

Parity target: ``realhf/apps/main.py:118-180`` (the reference's
launcher-level restart loop) + ``worker_base.py`` lifecycle control —
except the reference relaunches the WHOLE experiment on any worker death.
Here death is classified by failure domain first:

 - **Stateless domain** (rollout workers, the gen-fleet process): all
   durable state lives elsewhere (ConsumedLog on disk, weights at the
   trainer, quota reconstructable by the manager). These are respawned IN
   PLACE with exponential backoff; the respawn rejoins through
   name_resolve and the gserver manager's existing health-gate /
   re-admission / weight-reconcile machinery. A crash loop (more than
   ``RestartPolicy.max_restarts`` inside the rolling window) opens the
   circuit breaker and escalates.
 - **Stateful domain** (trainer, master): optimizer state and the step
   counter live there; an in-place respawn cannot rejoin a running step.
   Death escalates as :class:`SupervisorEscalation`, which
   ``run_experiment``'s ``recover_mode=auto`` loop converts into a
   whole-experiment relaunch from the last recover checkpoint.

An **unexpected clean exit** (exit code 0 of a required worker that was
never asked to exit) is a failure too: a rollout worker silently exiting
early would otherwise leave the master blocked on data-wait forever.

Liveness is grounded in name-resolve keepalive leases
(``name_resolve.add(..., keepalive_ttl=...)`` + ``touch``): the
supervisor stamps every child with an incarnation id and a TTL via the
environment (system/worker_base.py reads both), workers heartbeat their
advertisements from a dedicated thread, and before a respawn the
supervisor clears the dead incarnation's ghost keys so the control
panel, the manager, and the streams never address a corpse.

Restart counts, crash-loop state, heartbeat ages, and the drain phase
are exported through the PR 4 telemetry registry
(``supervisor_restarts_total{worker_kind=...}`` etc. on the merged
Prometheus scrape), and an escalation dumps the flight-recorder ring.
"""

from __future__ import annotations

import dataclasses
import json
import multiprocessing as mp
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from areal_tpu.base import logging, name_resolve, names, telemetry

logger = logging.getLogger("system.supervisor")

# Failure domains (docs/fault_tolerance.md §Failure domains).
# "gen_server" is a dynamically-scaled single generation server spawned by
# the autoscale executor (system/autoscaler.py) — stateless like the fleet
# process, but additionally *expendable*: a crash loop removes it from the
# fleet permanently instead of escalating (the autoscaler replaces it).
STATELESS_KINDS = ("rollout", "gen_fleet", "gen_server")


class SupervisorEscalation(RuntimeError):
    """A death the supervisor cannot absorb: stateful-domain worker died,
    or a stateless worker crash-looped past the circuit breaker. The
    launcher lets this propagate so ``run_experiment``'s recover loop
    relaunches the whole experiment."""


@dataclasses.dataclass
class RestartPolicy:
    """Per-worker respawn policy for the stateless domain."""

    max_restarts: int = 3  # per rolling window, then escalate
    window_secs: float = 300.0
    backoff_base_secs: float = 0.5
    backoff_max_secs: float = 30.0
    backoff_multiplier: float = 2.0

    def backoff(self, n_recent_restarts: int) -> float:
        return min(
            self.backoff_base_secs
            * self.backoff_multiplier ** max(n_recent_restarts - 1, 0),
            self.backoff_max_secs,
        )

    @classmethod
    def from_config(cls, ft) -> "RestartPolicy":
        """Build from an api.train_config.FaultToleranceConfig-shaped
        object (getattr-based: plain test configs work too)."""
        return cls(
            max_restarts=getattr(ft, "max_restarts", 3),
            window_secs=getattr(ft, "restart_window_secs", 300.0),
            backoff_base_secs=getattr(ft, "backoff_base_secs", 0.5),
            backoff_max_secs=getattr(ft, "backoff_max_secs", 30.0),
            backoff_multiplier=getattr(ft, "backoff_multiplier", 2.0),
        )


@dataclasses.dataclass
class WorkerSpec:
    """One supervised child process."""

    name: str  # worker-control name ("rollout0", "gen_fleet", "trainer0")
    kind: str  # failure-domain key ("rollout" | "gen_fleet" | "trainer")
    target: Callable  # module-level fn (mp spawn pickles it)
    args: Tuple = ()
    # A required worker exiting 0 without an exit request is a failure
    # (the master would block on data-wait forever, not crash).
    required: bool = True
    # An expendable worker (autoscaler-spawned generation server) that
    # crash-loops past the circuit breaker is PERMANENTLY REMOVED from
    # supervision instead of escalating to a whole-experiment relaunch —
    # the fleet plan replaces it with a fresh spec within its bounds, and
    # one flapping server never takes the run down with it.
    expendable: bool = False


class _Entry:
    __slots__ = ("spec", "proc", "incarnation", "restarts", "respawn_due",
                 "done")

    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self.proc = None
        self.incarnation = 0
        self.restarts: List[float] = []  # clock() stamps, pruned to window
        self.respawn_due: Optional[float] = None
        self.done = False  # death already handled / expected


class Supervisor:
    """Spawn + monitor the launcher's child workers.

    ``check()`` is called from the launcher's monitor loop (~1 Hz). It
    never sleeps: respawns are *scheduled* (``respawn_due``) and executed
    on the first check() past their backoff — so tests drive the whole
    state machine with an injected clock and fake processes.
    """

    def __init__(self, experiment: str, trial: str,
                 policy: Optional[RestartPolicy] = None,
                 keepalive_ttl: float = 0.0,
                 heartbeat_interval: float = 0.0,
                 restartable_kinds: Tuple[str, ...] = STATELESS_KINDS,
                 clock: Callable[[], float] = time.monotonic):
        self.experiment = experiment
        self.trial = trial
        self.policy = policy or RestartPolicy()
        self.keepalive_ttl = keepalive_ttl
        self.heartbeat_interval = heartbeat_interval
        self.restartable_kinds = tuple(restartable_kinds)
        self.clock = clock
        self._entries: Dict[str, _Entry] = {}
        self._draining = False
        self.restart_counts: Dict[str, int] = {}  # kind -> total respawns
        self._last_hb_export = 0.0
        # Wall-clock birth: shutdown markers (experiment finishing, drain
        # phases) older than this belong to a PREVIOUS incarnation of the
        # trial and must not suppress real failure detection.
        self._t_start_wall = time.time()

    # ---------------- spawning ----------------

    def spawn(self, spec: WorkerSpec) -> None:
        entry = _Entry(spec)
        self._entries[spec.name] = entry
        self._start(entry)

    def _start(self, entry: _Entry) -> None:
        entry.incarnation += 1
        entry.done = False
        entry.proc = self._make_proc(entry.spec, entry.incarnation)
        logger.info(
            f"spawned {entry.spec.name} (kind={entry.spec.kind}, "
            f"incarnation {entry.incarnation}, pid {entry.proc.pid})"
        )

    def _make_proc(self, spec: WorkerSpec, incarnation: int):
        """Start the actual OS process (tests override this with fakes).
        The incarnation id and keepalive TTL travel via the environment —
        mp's spawn snapshot picks them up before the child imports
        anything (system/worker_base.py reads them back)."""
        from areal_tpu.system import worker_base as wb

        ctx = mp.get_context("spawn")
        saved = {
            k: os.environ.get(k)
            for k in (wb.ENV_INCARNATION, wb.ENV_KEEPALIVE_TTL,
                      wb.ENV_HEARTBEAT_INTERVAL)
        }
        os.environ[wb.ENV_INCARNATION] = str(incarnation)
        if self.keepalive_ttl > 0:
            os.environ[wb.ENV_KEEPALIVE_TTL] = repr(self.keepalive_ttl)
        if self.heartbeat_interval > 0:
            os.environ[wb.ENV_HEARTBEAT_INTERVAL] = repr(
                self.heartbeat_interval
            )
        try:
            p = ctx.Process(target=spec.target, args=spec.args,
                            daemon=True, name=spec.name)
            p.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return p

    # ---------------- monitoring ----------------

    def procs(self) -> List:
        return [e.proc for e in self._entries.values()
                if e.proc is not None]

    def alive_count(self, kind: str) -> int:
        """Supervised workers of ``kind`` still in the fleet: running,
        freshly dead awaiting classification, or scheduled for respawn.
        Cleanly-exited and permanently-removed (expendable crash-loop)
        entries don't count — that's how the autoscale executor sees
        capacity it must replace."""
        return sum(
            1 for e in self._entries.values()
            if e.spec.kind == kind and not e.done
        )

    def begin_drain(self) -> None:
        """Planned teardown from here on: child exits (any code) are
        expected and never restarted or escalated."""
        self._draining = True
        for e in self._entries.values():
            e.respawn_due = None
        telemetry.set_gauge("supervisor/draining", 1.0)

    def check(self) -> None:
        """One supervision sweep: execute due respawns, classify new
        deaths, export heartbeat ages. Raises SupervisorEscalation for
        the stateful domain and for tripped circuit breakers."""
        now = self.clock()
        for entry in self._entries.values():
            if entry.respawn_due is not None:
                if now >= entry.respawn_due and not self._draining:
                    self._respawn(entry)
                continue
            p = entry.proc
            if p is None or entry.done or p.is_alive():
                continue
            code = p.exitcode
            if self._draining or (code == 0 and not entry.spec.required):
                entry.done = True
                continue
            if self._shutdown_signaled():
                # A commanded teardown is in progress that this process
                # didn't initiate: the master published its end-of-run
                # marker (its thread is still in the teardown tail when
                # the trainer's commanded exit lands here), or an
                # external `perf_probe drain` is walking the workers
                # down. Expected deaths — supervising them would
                # escalate a SUCCESSFUL run as a failure.
                logger.info(
                    f"{entry.spec.name} exited during a signaled "
                    f"shutdown/drain; treating as expected"
                )
                self.begin_drain()
                entry.done = True
                continue
            self._on_death(entry, code, now)
        self._export_heartbeats(now)

    def _shutdown_signaled(self) -> bool:
        """True iff a commanded teardown newer than this supervisor is
        advertised in name_resolve: the master's end-of-run marker
        (``experiment_status`` = finishing) or a graceful-drain phase
        written by ``drain_experiment`` — possibly driven EXTERNALLY
        (``perf_probe drain``), which this process otherwise cannot see.
        Consulted only when classifying an observed death (no
        steady-state polling cost); stale markers from a previous
        incarnation of the trial are ignored by timestamp."""
        for key in (
            names.experiment_status(self.experiment, self.trial),
            names.drain_status(self.experiment, self.trial),
        ):
            try:
                d = json.loads(name_resolve.get(key))
                if float(d.get("ts", 0.0)) >= self._t_start_wall:
                    return True
            except Exception:  # noqa: BLE001 — absent / torn: no signal
                pass
        return False

    def _on_death(self, entry: _Entry, code, now: float) -> None:
        spec = entry.spec
        reason = ("unexpected clean exit (exit 0 without an exit request)"
                  if code == 0 else f"exit code {code}")
        telemetry.inc(f"supervisor/deaths{{worker_kind={spec.kind}}}")
        if spec.kind not in self.restartable_kinds:
            self._escalate(
                entry, f"stateful worker {spec.name} died: {reason}; "
                f"escalating to whole-experiment recovery"
            )
        entry.restarts = [
            t for t in entry.restarts if now - t < self.policy.window_secs
        ]
        if len(entry.restarts) >= self.policy.max_restarts:
            telemetry.set_gauge(
                f"supervisor/crash_loop_open{{worker_kind={spec.kind}}}",
                1.0,
            )
            msg = (f"{spec.name} crash-looped: "
                   f"{len(entry.restarts)} restarts inside "
                   f"{self.policy.window_secs:.0f}s (last death: {reason}); "
                   f"circuit breaker open")
            if spec.expendable:
                # Flapping-server containment: the breaker trips, the
                # worker leaves the fleet for good, and nothing escalates
                # — the autoscale plan notices the lost capacity and
                # spawns a FRESH spec within its bounds.
                entry.done = True
                self._clear_ghost_keys(spec)
                telemetry.inc(
                    f"supervisor/removed{{worker_kind={spec.kind}}}"
                )
                t = telemetry.get()
                if t.enabled:
                    t.event("supervisor/removed", worker=spec.name,
                            kind=spec.kind, reason=msg)
                logger.error(
                    f"{msg}; permanently removed (expendable) — the "
                    f"autoscaler replaces it within bounds"
                )
                return
            self._escalate(entry, msg)
        entry.restarts.append(now)
        backoff = self.policy.backoff(len(entry.restarts))
        entry.respawn_due = now + backoff
        logger.warning(
            f"{spec.name} (kind={spec.kind}) died: {reason}; respawning "
            f"in {backoff:.2f}s "
            f"({len(entry.restarts)}/{self.policy.max_restarts} restarts "
            f"in window)"
        )

    def _escalate(self, entry: _Entry, msg: str) -> None:
        entry.done = True
        logger.error(msg)
        # Post-mortem evidence before the teardown: the launcher-process
        # flight ring (master spans, supervisor events) dumps now; the
        # per-worker SIGTERM hooks dump the survivors during shutdown.
        t = telemetry.get()
        if t.enabled:
            t.event("supervisor/escalate", worker=entry.spec.name,
                    kind=entry.spec.kind, reason=msg)
            t.flight_dump(reason=f"supervisor escalation: {msg}")
        raise SupervisorEscalation(msg)

    def _respawn(self, entry: _Entry) -> None:
        entry.respawn_due = None
        spec = entry.spec
        self._clear_ghost_keys(spec)
        self.restart_counts[spec.kind] = (
            self.restart_counts.get(spec.kind, 0) + 1
        )
        telemetry.inc(f"supervisor/restarts{{worker_kind={spec.kind}}}")
        self._start(entry)
        logger.warning(
            f"respawned {spec.name} (incarnation {entry.incarnation}); it "
            f"rejoins through name_resolve"
        )

    def _clear_ghost_keys(self, spec: WorkerSpec) -> None:
        """Delete the dead incarnation's registrations BEFORE the respawn
        binds fresh ones, so nothing resolves a corpse in the gap. The
        respawn re-adds its own keys with replace=True anyway; this
        closes the window for keys the new incarnation takes a while to
        re-register (the manager URL while servers re-prefill, say)."""
        from areal_tpu.system.worker_base import worker_control_key

        doomed = [
            worker_control_key(self.experiment, self.trial, spec.name),
            names.worker_heartbeat(self.experiment, self.trial, spec.name),
        ]
        if spec.kind == "gen_server":
            # A dynamic single-server worker (autoscaler spawn): its
            # discovery registration keys by server_id, which the
            # launcher names the worker after ("genserver_<server_id>").
            sid = spec.name
            if sid.startswith("genserver_"):
                sid = sid[len("genserver_"):]
            doomed.append(names.gen_servers(self.experiment, self.trial,
                                            sid))
        if spec.kind == "gen_fleet":
            # The fleet process hosts the servers AND the manager: clear
            # their discovery keys so rollout clients fail fast and
            # re-resolve instead of retrying dead sockets.
            try:
                name_resolve.clear_subtree(names.gen_server_root(
                    self.experiment, self.trial
                ))
            except Exception:  # noqa: BLE001
                pass
            doomed.append(names.gen_server_manager(
                self.experiment, self.trial
            ))
            hb_root = names.worker_heartbeat_root(self.experiment,
                                                  self.trial)
            for key in self._safe_find(hb_root):
                worker = key[len(hb_root.rstrip("/")) + 1:]
                if worker.startswith(("genserver_", "gserver_manager")):
                    doomed.append(key)
        for key in doomed:
            try:
                name_resolve.delete(key)
            except Exception:  # noqa: BLE001 — already gone
                pass

    @staticmethod
    def _safe_find(root: str) -> List[str]:
        try:
            return name_resolve.find_subtree(root)
        except Exception:  # noqa: BLE001
            return []

    def _export_heartbeats(self, now: float) -> None:
        """Heartbeat-age gauges for the merged scrape (rate-limited: the
        NFS walk is a directory scan). Ages come from the heartbeat keys
        workers rewrite; a worker whose process is alive but whose
        heartbeat is stale is wedged, which process liveness can't see."""
        if not telemetry.enabled() or self.keepalive_ttl <= 0:
            return
        if now - self._last_hb_export < max(self.keepalive_ttl / 3, 1.0):
            return
        self._last_hb_export = now
        from areal_tpu.system.worker_base import read_heartbeats

        try:
            hbs = read_heartbeats(self.experiment, self.trial)
        except Exception:  # noqa: BLE001 — name-resolve hiccup
            return
        for worker, d in hbs.items():
            age = d.get("age_secs")
            if age is None:
                continue
            telemetry.set_gauge(
                f"supervisor/heartbeat_age_secs{{worker={worker}}}", age
            )
            if age > 3 * self.keepalive_ttl:
                logger.warning(
                    f"heartbeat of {worker} is {age:.0f}s old "
                    f"(ttl {self.keepalive_ttl:.0f}s) — wedged worker?"
                )

    # ---------------- teardown ----------------

    def shutdown(self, timeout: float = 10.0, orderly: bool = True) -> None:
        """First-line teardown is ORDERLY: ask workers with a control
        endpoint to exit (they drain in-flight work and report their
        quota), then terminate/kill whatever remains. ``orderly=False``
        skips straight to terminate (tests, escalation paths)."""
        self.begin_drain()
        asked: List = []  # procs we asked to exit: they earn a grace join
        if orderly:
            try:
                from areal_tpu.system.worker_base import WorkerControlPanel

                panel = None
                for entry in self._entries.values():
                    if (entry.proc is not None and entry.proc.is_alive()
                            and entry.spec.kind == "rollout"):
                        if panel is None:
                            panel = WorkerControlPanel(
                                self.experiment, self.trial, timeout=2.0
                            )
                        res = panel.try_command(entry.spec.name, "exit")
                        if res.get("ok"):
                            asked.append(entry.proc)
                if panel is not None:
                    panel.close()
            except Exception:  # noqa: BLE001 — fall back to terminate
                pass
        deadline = time.monotonic() + timeout / 2
        for p in asked:
            p.join(timeout=max(0.05, deadline - time.monotonic()))
        procs = self.procs()
        for p in procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + timeout / 2
        for p in procs:
            p.join(timeout=max(0.05, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()


# --------------------------------------------------------------------------
# graceful drain (SIGTERM / preemption path)
# --------------------------------------------------------------------------


def _set_drain_phase(experiment: str, trial: str, phase: str) -> None:
    try:
        name_resolve.add(
            names.drain_status(experiment, trial),
            json.dumps({"phase": phase, "ts": time.time()}),
            replace=True, delete_on_exit=False,
        )
    except Exception:  # noqa: BLE001 — status is advisory
        pass
    telemetry.event("supervisor/drain_phase", phase=phase)


def drain_experiment(experiment: str, trial: str,
                     timeout: float = 60.0, panel=None) -> Dict:
    """Preemption-aware graceful drain of a live experiment
    (docs/operations.md §Preemption drain):

      1. PAUSE the master FIRST — the pause lands at a step boundary
         (retried while it is busy inside a step; the in-flight step
         still has live data producers), after which it serves further
         control commands from inside its paused loop and, crucially,
         never STARTS another step. Ordering matters: pausing the data
         producers first would starve a mid-step master that then never
         reaches its control channel — a drain deadlock.
      2. PAUSE every rollout worker — no new rollouts are issued;
         in-flight ones keep running on the workers' event loops and
         complete (pause only blocks the scheduling loop).
      3. Out-of-band recover CHECKPOINT via the master's ``checkpoint``
         control command, served while paused. No MFC is in flight
         (the master is parked between steps), so the trainer RPC is
         safe — and the trainer is deliberately never paused: it has to
         serve this checkpoint and the master's final exit RPC.
      4. Orderly EXIT: the master first (exit overrides pause; it breaks
         out of its loop WITHOUT executing another step, then its normal
         finalization tells the trainer to exit and closes the
         aggregator), then the rollout workers — whose shutdown path
         cancels stragglers and reports ``/finish_rollout``.

    The gen-fleet process has no control endpoint; the launcher
    terminates it after the master returns (it holds no durable state).
    Works against any live run via name_resolve — the launcher's SIGTERM
    handler and ``tools/perf_probe.py drain`` both call this.
    """
    from areal_tpu.system.worker_base import WorkerControlPanel

    own_panel = panel is None
    if panel is None:
        panel = WorkerControlPanel(experiment, trial,
                                   timeout=min(timeout / 4, 15.0))
    report: Dict = {"paused": {}, "checkpoint": None, "exited": []}
    deadline = time.monotonic() + timeout

    def _retry_command(worker: str, cmd: str) -> Dict:
        """Retry an IDEMPOTENT command while the worker is busy inside a
        step (its control channel is only served between iterations)."""
        while True:
            try:
                return panel.command(worker, cmd)
            except TimeoutError as e:
                if time.monotonic() >= deadline:
                    return {"ok": False, "error": str(e)}

    try:
        workers = panel.list_workers()
        rollouts = [w for w in workers if w.startswith("rollout")]
        _set_drain_phase(experiment, trial, "pausing")
        if "master" in workers:
            report["paused"]["master"] = _retry_command("master", "pause")
        for w in rollouts:
            report["paused"][w] = panel.try_command(w, "pause")
        if "master" in workers:
            _set_drain_phase(experiment, trial, "checkpoint")
            # Checkpoint is NOT idempotent-cheap: a retry-on-timeout
            # would queue redundant full checkpoints behind a slow one
            # and report failure while they all succeed. The master is
            # already paused (its control loop serves continuously), so
            # the only latency is the checkpoint itself: send ONCE on a
            # dedicated panel whose receive window is the remaining
            # drain budget.
            ck_panel = WorkerControlPanel(
                experiment, trial,
                timeout=max(deadline - time.monotonic(), 1.0),
            )
            try:
                report["checkpoint"] = ck_panel.command(
                    "master", "checkpoint"
                )
            except TimeoutError as e:
                report["checkpoint"] = {
                    "ok": False,
                    "error": f"{e} (checkpoint may still be running; "
                             f"NOT re-sent — it is not idempotent-cheap)",
                }
            finally:
                ck_panel.close()
        _set_drain_phase(experiment, trial, "exiting")
        if "master" in workers:
            res = panel.try_command("master", "exit")
            if res.get("ok"):
                report["exited"].append("master")
        for w in rollouts:
            res = panel.try_command(w, "exit")
            if res.get("ok"):
                report["exited"].append(w)
        _set_drain_phase(experiment, trial, "done")
    finally:
        if own_panel:
            panel.close()
    logger.info(f"graceful drain complete: {report}")
    return report
