"""Goodput ledger: live per-chip utilization truth for the async pipeline.

The paper's core claim — decoupling generation from training keeps every
chip busy — was only measurable at bench time: ``bench.py`` computed one
aggregate MFU after the fact, live runs exported phase *durations* (PR 4
spans) but no achieved-FLOP/s and no idle/compute decomposition. This
module turns the existing telemetry into a continuously exported
utilization signal, in three layers (docs/observability.md §Goodput):

 - :class:`GoodputLedger` — per-worker time-in-state accounting. Each
   worker classifies its wall clock into ``compute / comm / data_wait /
   idle`` monotonic counters (derived from the same structure the PR 4/7
   spans already trace: trainer split_pack|fwd_bwd|optimizer vs data-wait
   vs weight-publish; generation server prefill/decode vs queue-empty
   idle vs weight-update; rollout worker gate-wait vs grading vs
   generation-wait), exported into the worker's telemetry registry as
   ``goodput/secs{state=...}`` counters — ``areal_goodput_secs_total``
   on the scrape, so Prometheus ``rate()`` yields live utilization
   fractions without any server-side windowing.
 - :class:`MfuEmitter` + :func:`resolve_peak_flops` — live achieved
   FLOP/s and MFU gauges against the per-generation peak table
   (``base/monitor.py`` — the ONE home of the FLOPs formulas, shared
   with ``bench.py``). On an unknown device kind the emitter degrades to
   achieved-TFLOP/s-only with a one-time warning instead of exporting
   ``mfu=0.0`` (a hard zero would trip baseline sentinel rules as a
   false divergence).
 - :class:`FleetGoodput` — master-side stitching inside the
   TelemetryAggregator: useful chip-seconds / total chip-seconds over
   the merged worker counters, split trainer vs generation side,
   exported as ``areal_fleet_goodput{side=...}`` gauges on the merged
   scrape (and periodically into ``telemetry.jsonl``) — the async
   overlap claim as a single number an operator can watch.

Disabled contract (``goodput.enabled=false``, the default): every worker
gets the shared :data:`NULL_LEDGER` — no clock reads, no counters, no
MFU math — and the aggregator receives no FleetGoodput, so hot paths
carry zero new work and the scrape stays bit-identical.

Accounting semantics: a ledger holds ONE current state behind a lock;
``enter``/``state`` transitions partition wall clock exactly (the state
totals always sum to the elapsed wall time — the invariant the fake
clock tests pin). The partition must have a SINGLE owner: two
concurrent enter/restore pairs interleaving restore stale states and
can wedge the partition (a weight update restoring "compute" after the
decode already went idle would book every later queue-empty wait as
useful work). Work that overlaps the owner's partition therefore
ACCRUES via ``add(state, secs)`` instead of transitioning — the
generation server's weight updates (its runner loop owns idle↔compute
and re-anchors idle each iteration) and the rollout worker's N
concurrent rollout phases both do this. Accrued counters measure
task-seconds, which is also why :class:`FleetGoodput` folds only the
partition-owning chip kinds (trainer, generation_server) into fleet
goodput.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from areal_tpu.base import logging, telemetry

logger = logging.getLogger("system.goodput")

# The canonical state vocabulary. Ledgers accept other names (the export
# key is just an inline Prometheus label), but every built-in worker maps
# onto these four so fleet stitching is uniform across kinds.
GOODPUT_STATES = ("compute", "comm", "data_wait", "idle")

# Which worker kinds own accelerator chips — the only kinds folded into
# fleet goodput (CPU drivers like rollout workers export task-second
# counters that don't partition wall clock; see the module docstring).
TRAINER_SIDE_KINDS = frozenset({"trainer"})
GENERATION_SIDE_KINDS = frozenset({"generation_server"})

# The states that count as "useful" chip time in fleet goodput. comm
# (weight publish/consume) is overhead the async design exists to hide,
# so it is deliberately NOT useful — hiding it is the claim under test.
USEFUL_STATES = frozenset({"compute"})


def _counter_key(state: str) -> str:
    return f"goodput/secs{{state={state}}}"


def _overlap_key(state: str) -> str:
    return f"goodput/overlap_secs{{state={state}}}"


class GoodputLedger:
    """Thread-safe time-in-state accountant for one worker.

    Two modes share one export path:

    - wall-partition (``initial_state`` set, the default): ``enter(s)``
      closes the current state's interval and opens ``s``; the ``state``
      context manager restores the previous state on exit, so nesting
      (a weight publish inside an MFC) attributes correctly. Totals sum
      to wall clock exactly.
    - accrual-only (``initial_state=None``): no current state; callers
      ``add(state, secs)`` measured windows (task-seconds under
      concurrency).

    Exports are DELTAS into monotonic ``goodput/secs{state=...}``
    counters on the telemetry sink, rate-limited to
    ``export_interval_secs`` (transitions in between only accrue
    host-side floats).
    """

    enabled = True

    def __init__(self, sink, clock=time.monotonic,
                 export_interval_secs: float = 1.0,
                 initial_state: Optional[str] = "idle"):
        self._sink = sink
        self._clock = clock
        self._interval = max(float(export_interval_secs), 0.0)
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {s: 0.0 for s in GOODPUT_STATES}
        self._exported: Dict[str, float] = {}
        # Work overlapping the wall partition (add_overlap) — exported
        # as a SEPARATE goodput/overlap_secs family so the partition
        # states still sum to wall clock.
        self._overlap: Dict[str, float] = {}
        self._overlap_exported: Dict[str, float] = {}
        self._cur = initial_state
        now = clock()
        self._t_cur = now
        self._t_export = now

    # ---- wall-partition mode ----

    def enter(self, state: str) -> Optional[str]:
        """Switch to ``state``; returns the previous state (what a paired
        restore should re-enter). In accrual-only mode this STARTS the
        partition at ``state`` (no time is attributed retroactively)."""
        with self._lock:
            now = self._clock()
            prev = self._cur
            if prev is not None:
                self._totals[prev] = (
                    self._totals.get(prev, 0.0) + (now - self._t_cur)
                )
            self._cur = state
            self._t_cur = now
            self._maybe_export(now)
        return prev

    @contextmanager
    def state(self, state: str):
        """``with ledger.state("compute"):`` — enter ``state`` for the
        block, restore the previous state after (exception-safe)."""
        prev = self.enter(state)
        try:
            yield
        finally:
            if prev is not None:
                self.enter(prev)

    # ---- accrual-only mode ----

    def add(self, state: str, secs: float) -> None:
        """Accrue a caller-measured window (task-seconds; may overlap
        other windows under concurrency)."""
        if secs <= 0:
            return
        with self._lock:
            self._totals[state] = self._totals.get(state, 0.0) + float(secs)
            self._maybe_export(self._clock())

    def add_overlap(self, state: str, secs: float) -> None:
        """Accrue work that overlaps a wall-partition ledger's own
        timeline (a generation server's weight update racing decodes on
        the same event loop). Exported under the SEPARATE
        ``goodput/overlap_secs{state=...}`` family: folding it into the
        partition counters would make the states sum past wall clock —
        deflating every rate()-derived utilization fraction (and fleet
        goodput, which sums a chip worker's partition states as its
        denominator)."""
        if secs <= 0:
            return
        with self._lock:
            self._overlap[state] = (
                self._overlap.get(state, 0.0) + float(secs)
            )
            self._maybe_export(self._clock())

    # ---- shared ----

    def poll(self) -> None:
        """Fold the in-progress state's elapsed time into its total and
        export if due — serve loops call this so a long idle (or a long
        compute) shows up on the scrape before its closing transition."""
        with self._lock:
            now = self._clock()
            if self._cur is not None:
                self._totals[self._cur] = (
                    self._totals.get(self._cur, 0.0) + (now - self._t_cur)
                )
                self._t_cur = now
            self._maybe_export(now)

    def flush(self) -> None:
        """poll() + unconditional export (shutdown path)."""
        with self._lock:
            now = self._clock()
            if self._cur is not None:
                self._totals[self._cur] = (
                    self._totals.get(self._cur, 0.0) + (now - self._t_cur)
                )
                self._t_cur = now
            self._maybe_export(now, force=True)

    def totals(self) -> Dict[str, float]:
        """Accrued seconds per state (excluding the in-progress interval
        — call :meth:`poll` first for an up-to-the-instant view)."""
        with self._lock:
            return dict(self._totals)

    def _maybe_export(self, now: float, force: bool = False) -> None:
        # Called with self._lock held. The sink's own lock nests inside
        # ours and nothing ever takes them in the other order.
        if not force and now - self._t_export < self._interval:
            return
        self._t_export = now
        for s, v in self._totals.items():
            delta = v - self._exported.get(s, 0.0)
            if delta > 0:
                self._exported[s] = v
                self._sink.inc(_counter_key(s), delta)
        for s, v in self._overlap.items():
            delta = v - self._overlap_exported.get(s, 0.0)
            if delta > 0:
                self._overlap_exported[s] = v
                self._sink.inc(_overlap_key(s), delta)


class _NullLedger:
    """Shared disabled ledger: no clock reads, no counters, no locks."""

    enabled = False

    def enter(self, state: str) -> Optional[str]:
        return None

    @contextmanager
    def state(self, state: str):
        yield

    def add(self, state: str, secs: float) -> None:
        pass

    def add_overlap(self, state: str, secs: float) -> None:
        pass

    def poll(self) -> None:
        pass

    def flush(self) -> None:
        pass

    def totals(self) -> Dict[str, float]:
        return {}


NULL_LEDGER = _NullLedger()


def make_ledger(cfg, sink, clock=time.monotonic,
                initial_state: Optional[str] = "idle"):
    """Ledger for one worker, honoring the disabled contract: a missing/
    disabled :class:`~areal_tpu.api.train_config.GoodputConfig` — or a
    disabled telemetry sink (nowhere to export) — yields the shared null
    ledger, so call sites never branch."""
    if cfg is None or not getattr(cfg, "enabled", False):
        return NULL_LEDGER
    if sink is None or not getattr(sink, "enabled", False):
        return NULL_LEDGER
    return GoodputLedger(
        sink, clock=clock,
        export_interval_secs=getattr(cfg, "export_interval_secs", 1.0),
        initial_state=initial_state,
    )


# --------------------------------------------------------------------------
# live MFU gauges
# --------------------------------------------------------------------------


def resolve_peak_flops(cfg, device_kind: Optional[str] = None
                       ) -> Optional[float]:
    """Per-chip peak FLOP/s for live MFU: the config override when set,
    else the per-generation table (``monitor.device_peak_flops``), else
    None — unknown kinds degrade to achieved-TFLOP/s-only."""
    from areal_tpu.base import monitor

    override = float(getattr(cfg, "peak_flops_override", 0.0) or 0.0)
    if override > 0:
        return override
    return monitor.device_peak_flops(device_kind)


class MfuEmitter:
    """Publishes one (achieved-TFLOP/s, MFU) gauge pair.

    ``emit(flops_per_sec_per_chip)`` always sets the TFLOP/s gauge; the
    MFU gauge only exists when the peak is known. An unknown peak warns
    ONCE and then stays silent — exporting ``mfu=0.0`` instead would
    look like a real collapse to any rolling-baseline sentinel rule."""

    def __init__(self, sink, peak_flops: Optional[float],
                 tflops_name: str, mfu_name: str, context: str = ""):
        self._sink = sink
        self.peak = float(peak_flops) if peak_flops else None
        self._tflops_name = tflops_name
        self._mfu_name = mfu_name
        self._context = context
        self._warned = False

    def emit(self, flops_per_sec_per_chip: float) -> None:
        f = float(flops_per_sec_per_chip)
        if f <= 0:
            return
        self._sink.set_gauge(self._tflops_name, f / 1e12)
        if self.peak:
            self._sink.set_gauge(self._mfu_name, f / self.peak)
        elif not self._warned:
            self._warned = True
            logger.warning(
                f"{self._context or self._mfu_name}: unknown device peak "
                f"FLOP/s — exporting {self._tflops_name} only (no "
                f"{self._mfu_name} gauge). Set goodput.peak_flops_override "
                f"or extend base/monitor.TPU_PEAK_BF16."
            )


# --------------------------------------------------------------------------
# master-side fleet stitching
# --------------------------------------------------------------------------


class FleetGoodput:
    """Derives fleet goodput from the per-worker ledger counters flowing
    through the TelemetryAggregator.

    ``update(worker, counters)`` parses the cumulative
    ``goodput/secs{state=...}`` totals out of one ingested snapshot and
    recomputes useful chip-seconds / total chip-seconds over the
    chip-bearing workers — overall and split trainer vs generation side
    — into this object's registry (exported by the aggregator's merged
    /metrics as the ``fleet`` pseudo-worker). Returns the fresh gauge
    dict (for the sentinel feed), or None when the snapshot carried no
    ledger counters.

    The fraction is WINDOWED, not since-start: each worker keeps a short
    history of (time, cumulative totals) snapshots and contributes the
    delta over the last ``window_secs`` — a since-start average's
    sensitivity decays with run length, so six hours in, a fleet going
    fully idle would barely move the gauge (and the ``goodput_collapse``
    sentinel rule would never see the excursion it exists to catch). A
    cumulative total going BACKWARD (worker restart reset its counters)
    restarts that worker's baseline, and a worker that stops reporting
    for ``expiry_secs`` is dropped entirely — an evicted/scaled-down
    server's frozen history must not pin either side's fraction (same
    failure mode as the sentinel's ``source_expiry_secs``)."""

    def __init__(self, registry: Optional[Any] = None,
                 window_secs: float = 300.0, expiry_secs: float = 120.0,
                 clock=time.monotonic):
        self.registry = registry or telemetry.TelemetryRegistry()
        self.window_secs = float(window_secs)
        self.expiry_secs = float(expiry_secs)
        self._clock = clock
        self._lock = threading.Lock()
        # worker "kind:index" -> list of (t, {state: cumulative secs}),
        # oldest first; [0] is the window baseline.
        self._hist: Dict[str, list] = {}
        # gauge names currently published into the registry — so a side
        # whose workers all expired is WITHDRAWN from the scrape rather
        # than pinned at its last (now fictional) value.
        self._published: set = set()

    @staticmethod
    def _ledger_totals(counters: Dict[str, float]) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for key, v in (counters or {}).items():
            base, labels = telemetry._metric_key_labels(key)
            if base != "goodput/secs" or not labels:
                continue
            state = labels.get("state")
            if state and isinstance(v, (int, float)):
                totals[state] = totals.get(state, 0.0) + float(v)
        return totals

    def _window_row(self, worker: str) -> Dict[str, float]:
        """One worker's per-state seconds over the window: latest
        cumulative minus the baseline snapshot (a first/just-reset
        worker contributes its full since-start totals)."""
        hist = self._hist[worker]
        latest = hist[-1][1]
        base = hist[0][1] if len(hist) >= 2 else {}
        return {
            s: max(v - base.get(s, 0.0), 0.0) for s, v in latest.items()
        }

    @staticmethod
    def _fraction(rows) -> Optional[float]:
        total = sum(sum(t.values()) for t in rows)
        if total <= 0:
            return None
        useful = sum(
            v for t in rows for s, v in t.items() if s in USEFUL_STATES
        )
        return useful / total

    def update(self, worker: str,
               counters: Dict[str, float]) -> Optional[Dict[str, float]]:
        totals = self._ledger_totals(counters)
        if not totals:
            return None
        now = self._clock()
        with self._lock:
            hist = self._hist.setdefault(worker, [])
            if hist and any(
                totals.get(s, 0.0) < v - 1e-9
                for s, v in hist[-1][1].items()
            ):
                hist.clear()  # counter reset: the worker restarted
            hist.append((now, totals))
            # Trim so [0] stays the newest sample at/before the window
            # start (the delta baseline); everything older is dead.
            while len(hist) >= 2 and hist[1][0] <= now - self.window_secs:
                hist.pop(0)
            # Expire departed workers (evicted / scaled-down): their
            # frozen totals must not pin the fractions forever.
            for w in [w for w, h in self._hist.items()
                      if now - h[-1][0] > self.expiry_secs]:
                del self._hist[w]
            trainer_rows = [
                self._window_row(w) for w in self._hist
                if w.partition(":")[0] in TRAINER_SIDE_KINDS
            ]
            gen_rows = [
                self._window_row(w) for w in self._hist
                if w.partition(":")[0] in GENERATION_SIDE_KINDS
            ]
        gauges: Dict[str, float] = {}
        fleet = self._fraction(trainer_rows + gen_rows)
        if fleet is not None:
            gauges["fleet/goodput"] = fleet
        t = self._fraction(trainer_rows)
        if t is not None:
            gauges["fleet/goodput{side=trainer}"] = t
        g = self._fraction(gen_rows)
        if g is not None:
            gauges["fleet/goodput{side=generation}"] = g
        gauges["fleet/goodput_workers"] = float(
            len(trainer_rows) + len(gen_rows)
        )
        for k in self._published - set(gauges):
            self.registry.remove_gauge(k)
        self._published = set(gauges)
        for k, v in gauges.items():
            self.registry.set_gauge(k, v)
        # Non-chip kinds (rollout task-seconds) still land in _hist —
        # visible per-worker on the scrape — without skewing either
        # side's fraction.
        return gauges

    def gauges(self) -> Dict[str, float]:
        return dict(self.registry.snapshot(reset=False)["gauges"])
