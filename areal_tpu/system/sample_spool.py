"""Durable trajectory spool — at-least-once rollout→trainer delivery.

The async data plane (streams.ZmqPusher → trainer ZmqPuller) is
fire-and-forget: a trainer death destroys every in-flight trajectory
while the rollout worker's ConsumedLog durably guarantees those prompts
are never regenerated — permanent sample loss. This module closes the
hole (docs/fault_tolerance.md §Data durability):

 - :class:`SampleSpool` — per-rollout-worker append-only segment log.
   Every accepted trajectory is fsynced here BEFORE the prompt is marked
   consumed, so the crash-ordering invariant "consumed ⇒ spooled" holds
   at every instruction boundary. Records carry a CRC and the reader
   repairs a torn tail exactly like the ConsumedLog (a record that never
   fully landed is dropped — safe: the prompt was not yet consumed).
 - :class:`SpoolSender` — background thread that drains the spool to the
   ZMQ push socket (non-blocking sends; a dead trainer can no longer
   wedge the asyncio loop inside ``pusher.push``), receives acks on a
   per-worker ack channel, truncates acked segment prefixes, and
   re-sends records whose ack never arrived (trainer restart).
 - :class:`SpoolIngest` — trainer-side idempotent ingest decision:
   dedup by sample id (duplicates are a normal at-least-once event),
   staleness gate for replays, and the trained/durably-dropped → ack
   bookkeeping the trainer's "clear" handler drives.

Wire compatibility: pushes gain an OPTIONAL ``_spool`` key
(``{"w": worker_index, "seq": seqno}``, plus ``"r": 1`` on re-sends),
mirroring the telemetry ``_trace`` contract — with durability disabled
nothing is injected and the wire bytes are bit-identical to today's
format (pinned by tests/test_sample_spool.py).
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from areal_tpu.base import logging, telemetry

logger = logging.getLogger("system.sample_spool")

SPOOL_KEY = "_spool"

# Record layout: 24-byte header + payload.
#   >Q  seqno       (monotonic from 1; also the ack unit)
#   >d  wall time   (oldest-unacked-age accounting survives restarts)
#   >I  payload len
#   >I  crc32 over (first 20 header bytes + payload)
_HDR = struct.Struct(">QdI")
_CRC = struct.Struct(">I")
_HDR_BYTES = _HDR.size + _CRC.size


def ack_channel_name(worker_index: int) -> str:
    """name_resolve puller name for rollout worker ``worker_index``'s ack
    channel (trainer pushes ``{"seqnos": [...]}`` dicts to it)."""
    return f"spool_ack_{worker_index}"


class SpoolFull(RuntimeError):
    """Raised by ``append`` when the spool is at ``max_bytes`` —
    backpressure: the caller waits for acks to free space instead of
    growing the disk footprint without bound."""


@dataclasses.dataclass
class SpoolStats:
    depth: int  # unacked records
    bytes: int  # live segment bytes on disk
    oldest_unacked_age_secs: float  # 0.0 when empty
    acked_watermark: int
    next_seqno: int


@dataclasses.dataclass
class _Segment:
    path: str
    first: int  # first seqno in the file
    last: int  # last seqno written (first-1 when empty)
    nbytes: int


class SampleSpool:
    """Append-only segment spool with a durable contiguous-ack watermark.

    Durability contract (the whole point — see ConsumedLog): ``append``
    returns only after the record is flushed AND fsynced, so the caller
    may mark the prompt consumed knowing the trajectory can always be
    replayed. The ack watermark file is written atomically (tmp+rename)
    but NOT fsynced per ack: losing it merely replays extra records,
    which the trainer's idempotent ingest absorbs — the safe direction.

    Unacked payloads are also kept in memory (bounded by ``max_bytes``,
    the same bound as the disk footprint) so the sender never re-reads
    the segment files on the hot path; a restart reloads them from disk.

    Thread-safe: the asyncio loop appends (via ``asyncio.to_thread``)
    while the sender thread acks and reads pending records.
    """

    def __init__(self, directory: str, segment_bytes: int = 8 << 20,
                 max_bytes: int = 256 << 20):
        if segment_bytes <= 0 or max_bytes < segment_bytes:
            raise ValueError(
                f"spool needs 0 < segment_bytes ({segment_bytes}) <= "
                f"max_bytes ({max_bytes})"
            )
        self.dir = directory
        self.segment_bytes = segment_bytes
        self.max_bytes = max_bytes
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._fh = None  # active segment file handle (append mode)
        self._segments: List[_Segment] = []
        self._recs: Dict[int, Tuple[float, bytes]] = {}  # seqno -> (ts, raw)
        self._acked_above: set = set()  # acked but > watermark (gap acks)
        self._watermark = self._read_watermark()
        self._next = self._watermark + 1
        self._bytes = 0
        self._closed = False
        self._recover()

    # ---------------- recovery ----------------

    @property
    def _wm_path(self) -> str:
        return os.path.join(self.dir, "acked")

    def _read_watermark(self) -> int:
        try:
            with open(self._wm_path) as f:
                return int(f.read().strip() or 0)
        except (FileNotFoundError, ValueError):
            return 0

    def _write_watermark(self) -> None:
        tmp = self._wm_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(self._watermark))
        os.replace(tmp, self._wm_path)

    def _recover(self) -> None:
        """Scan existing segments: rebuild the unacked record map, repair
        a torn tail (crash mid-append — the record never fully landed, so
        it is dropped; by the spool-before-consumed ordering its prompt
        was not yet consumed and re-trains once, the safe direction)."""
        names = sorted(
            n for n in os.listdir(self.dir)
            if n.startswith("seg-") and n.endswith(".spool")
        )
        expected = None
        for name in names:
            path = os.path.join(self.dir, name)
            raw = open(path, "rb").read()
            off = 0
            first = int(name[len("seg-"):-len(".spool")])
            if expected is not None and first != expected:
                logger.error(
                    f"spool {self.dir}: segment {name} starts at {first}, "
                    f"expected {expected} — dropping it and everything "
                    f"after (mid-chain corruption)"
                )
                os.remove(path)
                continue
            seg = _Segment(path, first, first - 1, 0)
            while off + _HDR_BYTES <= len(raw):
                seqno, ts, length = _HDR.unpack_from(raw, off)
                (crc,) = _CRC.unpack_from(raw, off + _HDR.size)
                end = off + _HDR_BYTES + length
                if end > len(raw):
                    break  # torn payload
                payload = raw[off + _HDR_BYTES:end]
                if crc != zlib.crc32(raw[off:off + _HDR.size] + payload):
                    break  # torn/corrupt record
                if seqno != seg.last + 1:
                    break  # sequence break: treat like corruption
                seg.last = seqno
                seg.nbytes += end - off
                if seqno > self._watermark:
                    self._recs[seqno] = (ts, payload)
                off = end
            if off < len(raw):
                logger.warning(
                    f"spool {self.dir}: truncating torn tail of {name} "
                    f"at byte {off} (crash mid-append); the dropped "
                    f"record was never marked consumed"
                )
                with open(path, "rb+") as f:
                    f.truncate(off)
            if seg.last < seg.first:  # nothing valid in the file
                os.remove(path)
                continue
            self._segments.append(seg)
            self._bytes += seg.nbytes
            expected = seg.last + 1
        if self._segments:
            self._next = max(self._next, self._segments[-1].last + 1)
        # Segments fully below the watermark survived a crash between
        # the ack and the delete — drop them now.
        self._gc_locked()

    # ---------------- append ----------------

    def append(self, payload: bytes, ts: Optional[float] = None) -> int:
        """Durably append one record; returns its seqno. Raises
        :class:`SpoolFull` when ``max_bytes`` would be exceeded."""
        return self.append_framed(lambda seqno: payload, ts=ts)

    def append_framed(self, frame: Callable[[int], bytes],
                      ts: Optional[float] = None) -> int:
        """Like ``append`` but the payload may embed its own seqno:
        ``frame(seqno) -> bytes`` runs under the spool lock, so the
        seqno order always matches the on-disk record order."""
        with self._lock:
            if self._closed:
                raise RuntimeError("spool is closed")
            seqno = self._next
            payload = frame(seqno)
            size = _HDR_BYTES + len(payload)
            if self._bytes + size > self.max_bytes:
                raise SpoolFull(
                    f"spool at {self._bytes}B (+{size}B > "
                    f"{self.max_bytes}B cap): trainer acks are not "
                    f"keeping up"
                )
            ts = time.time() if ts is None else ts
            hdr20 = _HDR.pack(seqno, ts, len(payload))
            rec = hdr20 + _CRC.pack(zlib.crc32(hdr20 + payload)) + payload
            fh = self._active_segment(seqno)
            fh.write(rec)
            fh.flush()
            os.fsync(fh.fileno())
            self._segments[-1].last = seqno
            self._segments[-1].nbytes += len(rec)
            self._bytes += len(rec)
            self._recs[seqno] = (ts, payload)
            self._next = seqno + 1
            return seqno

    def _active_segment(self, next_seqno: int):
        if self._fh is not None \
                and self._segments[-1].nbytes >= self.segment_bytes:
            self._fh.close()
            self._fh = None
        if self._fh is None:
            # Always a fresh file (named by its first seqno): a restarted
            # worker starts a new segment rather than appending to the
            # recovered tail, keeping the name↔first-seqno invariant.
            path = os.path.join(self.dir, f"seg-{next_seqno:016d}.spool")
            self._fh = open(path, "ab")
            if not self._segments or self._segments[-1].path != path:
                self._segments.append(
                    _Segment(path, next_seqno, next_seqno - 1, 0)
                )
        return self._fh

    # ---------------- ack / read ----------------

    def ack(self, seqnos: Sequence[int]) -> int:
        """Mark records delivered-and-settled (trained or durably
        dropped); returns how many were newly acked. Advances the
        contiguous watermark and deletes fully-acked segment prefixes."""
        with self._lock:
            n_new = 0
            for s in seqnos:
                s = int(s)
                if s <= self._watermark or s in self._acked_above \
                        or s >= self._next:
                    continue
                self._acked_above.add(s)
                self._recs.pop(s, None)
                n_new += 1
            advanced = False
            while self._watermark + 1 in self._acked_above:
                self._watermark += 1
                self._acked_above.discard(self._watermark)
                advanced = True
            if advanced:
                self._write_watermark()
                self._gc_locked()
            if n_new:
                self._space.notify_all()
            return n_new

    def _gc_locked(self) -> None:
        keep: List[_Segment] = []
        for seg in self._segments:
            if seg.last <= self._watermark:
                if self._fh is not None and self._fh.name == seg.path:
                    self._fh.close()
                    self._fh = None
                try:
                    os.remove(seg.path)
                except FileNotFoundError:
                    pass
                self._bytes -= seg.nbytes
            else:
                keep.append(seg)
        self._segments = keep

    def wait_for_space(self, timeout: float) -> bool:
        """Block until an ack frees space (or timeout); used by the
        submit path's backpressure loop."""
        with self._space:
            return self._space.wait(timeout)

    def pending(self, after: int = 0) -> List[Tuple[int, float, bytes]]:
        """Unacked records with seqno > ``after``, in seqno order."""
        with self._lock:
            return sorted(
                (s, ts, raw) for s, (ts, raw) in self._recs.items()
                if s > after
            )

    def unacked_seqnos(self) -> List[int]:
        with self._lock:
            return sorted(self._recs)

    def stats(self) -> SpoolStats:
        with self._lock:
            oldest = min((ts for ts, _ in self._recs.values()), default=None)
            return SpoolStats(
                depth=len(self._recs),
                bytes=self._bytes,
                oldest_unacked_age_secs=(
                    max(0.0, time.time() - oldest) if oldest else 0.0
                ),
                acked_watermark=self._watermark,
                next_seqno=self._next,
            )

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self._space.notify_all()


class SpoolSender(threading.Thread):
    """Background drain of a :class:`SampleSpool` to the trainer.

    Owns the worker's data-plane sockets once started: the asyncio loop
    only ever calls :meth:`submit` (durable enqueue, via
    ``asyncio.to_thread``) — ZMQ I/O, ack processing, the resend timer,
    and the spool gauges all live on this thread, so a dead/slow trainer
    degrades into spool growth + backpressure instead of wedging the
    event loop inside a blocking ``send``.

    Ack loss is self-healing: any record unacked for
    ``resend_timeout_secs`` after its last send is pushed again with the
    replay flag set; the trainer's :class:`SpoolIngest` dedups and
    re-acks. Records found in the spool at startup (a respawned worker)
    are replays by definition and are re-sent the same way.
    """

    def __init__(self, spool: SampleSpool, pusher, ack_puller,
                 worker_index: int, resend_timeout_secs: float = 30.0,
                 poll_secs: float = 0.05):
        super().__init__(name=f"spool-sender-{worker_index}", daemon=True)
        self.spool = spool
        self.pusher = pusher
        self.ack_puller = ack_puller
        self.worker_index = worker_index
        self.resend_timeout_secs = resend_timeout_secs
        self.poll_secs = poll_secs
        self._wake = threading.Event()
        self._closing = threading.Event()
        self._last_sent = spool.stats().acked_watermark
        self._sent_at: Dict[int, float] = {}
        # Everything already in the spool predates this incarnation:
        # crash-replay records, flagged so the trainer's staleness gate
        # sees them (fresh sends just passed the manager's gate).
        self._replay = set(spool.unacked_seqnos())
        self._gauges_at = 0.0

    # ---- producer side (asyncio loop, via to_thread) ----

    def submit(self, obj: Dict[str, Any]) -> int:
        """Durably spool one trajectory payload; returns its seqno. The
        active telemetry trace is captured here (contextvars propagate
        through ``asyncio.to_thread``), exactly like the direct-push
        path. Blocks under backpressure until acks free spool space."""
        obj = telemetry.inject_payload(obj)

        def frame(seqno: int) -> bytes:
            from areal_tpu.system.streams import _pack

            obj[SPOOL_KEY] = {"w": self.worker_index, "seq": seqno}
            return _pack(obj)

        while True:
            try:
                seqno = self.spool.append_framed(frame)
                break
            except SpoolFull:
                telemetry.inc("spool/backpressure_waits")
                if self._closing.is_set():
                    raise
                self.spool.wait_for_space(0.5)
        telemetry.inc("spool/appended")
        self._wake.set()
        return seqno

    # ---- sender thread ----

    def _drain_acks(self) -> None:
        while True:
            try:
                msg = self.ack_puller.pull(timeout_ms=0)
            except Exception:  # noqa: BLE001 — socket closed during exit
                return
            if msg is None:
                return
            seqnos = msg.get("seqnos") if isinstance(msg, dict) else None
            if not seqnos:
                continue
            n = self.spool.ack(seqnos)
            for s in seqnos:
                self._sent_at.pop(int(s), None)
            if n:
                telemetry.inc("spool/acked", n)

    def _send_raw(self, seqno: int, raw: bytes, replay: bool) -> bool:
        """One non-blocking send attempt; False = HWM, retry later."""
        if replay:
            # Re-sends re-frame with the replay flag so the trainer's
            # staleness gate examines them; first sends go out exactly
            # as spooled (zero repack on the hot path).
            from areal_tpu.system.streams import _pack, _unpack

            obj = _unpack(raw)
            meta = obj.get(SPOOL_KEY)
            if isinstance(meta, dict):
                meta["r"] = 1
            raw = _pack(obj)
        try:
            self.pusher.push_packed(raw, block_secs=0.0)
        except Exception:  # noqa: BLE001 — zmq.Again / transient
            return False
        self._sent_at[seqno] = time.monotonic()
        return True

    def _pump(self) -> None:
        self._drain_acks()
        # First sends (and restart replays) in seqno order.
        for seqno, _ts, raw in self.spool.pending(after=self._last_sent):
            replay = seqno in self._replay
            if not self._send_raw(seqno, raw, replay):
                return  # blocked at HWM; retry next tick
            if replay:
                telemetry.inc("spool/replayed")
                self._replay.discard(seqno)
            self._last_sent = max(self._last_sent, seqno)
        # Resend timer: an unacked record the trainer never settled
        # (death between pull and train, or a lost ack).
        now = time.monotonic()
        for seqno, _ts, raw in self.spool.pending(after=0):
            if seqno > self._last_sent:
                continue
            at = self._sent_at.get(seqno)
            if at is not None and now - at < self.resend_timeout_secs:
                continue
            if at is None and seqno in self._replay:
                continue  # still queued for its first (replay) send
            if not self._send_raw(seqno, raw, replay=True):
                return
            telemetry.inc("spool/resent")

    def _publish_gauges(self) -> None:
        now = time.monotonic()
        if now - self._gauges_at < 1.0:
            return
        self._gauges_at = now
        st = self.spool.stats()
        telemetry.set_gauge("spool/depth", float(st.depth))
        telemetry.set_gauge("spool/bytes", float(st.bytes))
        telemetry.set_gauge(
            "spool/oldest_unacked_age_secs", st.oldest_unacked_age_secs
        )

    def run(self) -> None:
        while not self._closing.is_set():
            try:
                self._pump()
                self._publish_gauges()
            except Exception as e:  # noqa: BLE001 — sender must survive
                logger.warning(f"spool sender pump failed ({e}); retrying")
                time.sleep(0.2)
            self._wake.wait(self.poll_secs)
            self._wake.clear()

    def close(self, drain_secs: float = 5.0) -> None:
        """Stop the sender, first giving in-flight acks ``drain_secs``
        to settle (a clean exit with an empty spool leaves nothing to
        replay next incarnation)."""
        deadline = time.monotonic() + max(drain_secs, 0.0)
        while self.spool.stats().depth > 0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        self._closing.set()
        self._wake.set()
        self.join(timeout=5.0)
        self._publish_gauges_final()
        self.spool.close()

    def _publish_gauges_final(self) -> None:
        self._gauges_at = 0.0
        try:
            self._publish_gauges()
        except Exception:  # noqa: BLE001 — registry already shut down
            pass


class SpoolIngest:
    """Trainer-side idempotent ingest bookkeeping (rank 0 only).

    ``observe`` classifies each arriving spooled sample; the pull loop
    acts on the verdict:

    - ``("ingest", None)`` — first sighting: enqueue for training; the
      ack is emitted later, when the master's freed-id forwarding (the
      "clear" RPC after the optimizer step commits, or a buffer-level
      durable drop) names the sample in :meth:`pop_settled`.
    - ``("duplicate", None)`` — the original is still in the pipeline:
      drop the copy silently; its ack rides the original's settlement.
    - ``("duplicate", (w, seq))`` — already settled here (the ack was
      lost in flight): re-ack immediately so the worker stops resending.
    - ``("stale", (w, seq))`` — a replay that fell behind the staleness
      bound while the trainer was down: durably dropped — count it and
      ack it (the paper's gate bounds off-policyness; replaying
      arbitrarily old trajectories would silently violate it).

    The ingested-id set grows for the life of the process (a few dozen
    bytes per trajectory — the same order as the ConsumedLog it
    mirrors); a trainer restart clears it, which is exactly when
    replayed ids must re-ingest.
    """

    def __init__(self, staleness_limit: int = 8):
        self.staleness_limit = staleness_limit
        self._lock = threading.Lock()
        self._ids: set = set()
        self._pending: Dict[Any, Tuple[int, int]] = {}

    def observe(self, sample_id: Any, meta: Dict[str, Any],
                cur_version: float,
                sample_version: Optional[float]) -> Tuple[
                    str, Optional[Tuple[int, int]]]:
        w, seq = int(meta["w"]), int(meta["seq"])
        with self._lock:
            if sample_id in self._ids:
                if sample_id in self._pending:
                    return "duplicate", None
                return "duplicate", (w, seq)
            if meta.get("r") and self.staleness_limit >= 0 \
                    and sample_version is not None \
                    and cur_version - sample_version > self.staleness_limit:
                # Remember the id: later resends of the same dropped
                # record hit the settled-duplicate path and re-ack.
                self._ids.add(sample_id)
                return "stale", (w, seq)
            self._ids.add(sample_id)
            self._pending[sample_id] = (w, seq)
            return "ingest", None

    def pop_settled(self, sample_ids: Sequence[Any]) -> Dict[int, List[int]]:
        """Sample ids the master reported freed (trained or durably
        dropped) → ``{worker_index: [seqnos]}`` to ack."""
        out: Dict[int, List[int]] = {}
        with self._lock:
            for sid in sample_ids:
                ws = self._pending.pop(sid, None)
                if ws is not None:
                    out.setdefault(ws[0], []).append(ws[1])
        return out
