"""Streamed weight sync: trainer → generation fleet over ZMQ, no disk.

The disk publish path (``trainer_worker.publish_weights`` →
``generation_server._load_and_put_weights``) round-trips every weight
through the filesystem: serialize + write on the trainer, read + parse on
every server. §3.5 of the source paper makes low-latency weight sync the
lynchpin of staleness control, and AReaL's NCCL update path / SGLang's
``update_weights_from_distributed`` both stream tensors directly instead.
This module is the TPU-native analogue over the repo's existing ZMQ fabric
(``streams.py`` socket idioms, ``names.py`` discovery).

Roles:

 - :class:`WeightStreamPublisher` (trainer, rank 0): holds a host-side
   cache of the published tensors and serves them to any number of
   consumers over a ROUTER socket — per-server replay from one d2h gather,
   the multi-subscriber fanout. ``publish()`` returns immediately; a
   background *gather* thread pulls tensors off the device one at a time
   (d2h of tensor *i+1* overlaps the wire transfer of tensor *i*, which
   the consumer overlaps with its ``device_put`` of tensor *i−1* — the
   three-leg pipeline).
 - :class:`WeightStreamConsumer` (generation server): fetches the manifest,
   streams chunks with a bounded window of in-flight requests, reassembles
   tensors, and verifies the whole transfer against the publisher's digest
   before the caller swaps anything live.

Wire protocol (REQ-less DEALER↔ROUTER, multipart frames):

 - ``[b"manifest", {"version": v}]`` → ``[b"ok", manifest-json]``
   Manifest: tensor names, shapes, dtypes, per-tensor byte counts and
   chunk counts, the wire chunk size, and the weight version.
 - ``[b"chunk", {"version", "tensor", "chunk"}]`` →
   ``[b"ok", {"tensor", "chunk", "crc32"}, payload]``
   Blocks (bounded) until the gather thread has produced that tensor.
 - ``[b"digest", {"version": v}]`` → ``[b"ok", {"crcs": [[...], ...]}]``
   Per-chunk CRC32s of the COMPLETE publish — available only once the
   gather finished, so a consumer that verifies its locally computed CRCs
   against the digest has proof the stream was neither torn nor reordered
   nor corrupted before it swaps.

Every reply echoes the (version, tensor, chunk) coordinates; a consumer
receiving an echo that does not match its request order aborts. Trust
model: intra-cluster, same as the pickled control plane in ``streams.py``
— checksums defend against torn/reordered/corrupted transfers, not
adversaries.
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import zmq

from areal_tpu.base import logging, name_resolve, names, network, telemetry

logger = logging.getLogger("system.weight_stream")

DEFAULT_CHUNK_BYTES = 32 << 20  # 32 MB wire chunks
DEFAULT_PIPELINE_DEPTH = 4  # in-flight chunk requests per consumer


class WeightStreamError(RuntimeError):
    """Torn / reordered / corrupted / timed-out weight stream."""


class _NotReady(Exception):
    """Internal: the request needs data the gather thread has not produced
    yet — the serve loop defers it instead of blocking (other consumers'
    requests keep flowing)."""


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, including the ml_dtypes extended types (bfloat16)
    that plain numpy does not resolve from strings."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _as_wire_array(leaf: Any) -> np.ndarray:
    """Host, contiguous view of a (possibly device-resident) tensor. For
    jax arrays this is the d2h transfer — called from the gather thread so
    it overlaps the wire leg of previously gathered tensors."""
    return np.ascontiguousarray(np.asarray(leaf))


class _PublishedVersion:
    """Host cache of one published weight version."""

    def __init__(self, version: int, tensors: Sequence[Tuple[str, Any]],
                 chunk_bytes: int):
        self.version = version
        self.chunk_bytes = chunk_bytes
        self.names = [n for n, _ in tensors]
        self.leaves: List[Any] = [v for _, v in tensors]  # device refs
        self.arrays: List[Optional[np.ndarray]] = [None] * len(tensors)
        self.crcs: List[List[int]] = [[] for _ in tensors]
        # Shapes/dtypes are known without any d2h: manifests are servable
        # the moment publish() is called.
        self.shapes = [tuple(int(d) for d in np.shape(v)) for _, v in tensors]
        self.dtypes = [str(getattr(v, "dtype", None) or np.asarray(v).dtype)
                       for _, v in tensors]
        self.nbytes = [
            int(np.prod(s, dtype=np.int64)) * _np_dtype(d).itemsize
            for s, d in zip(self.shapes, self.dtypes)
        ]
        self.n_chunks = [
            max(1, -(-nb // chunk_bytes)) for nb in self.nbytes
        ]
        self.ready = [threading.Event() for _ in tensors]
        self.complete = threading.Event()
        self.gather_secs = 0.0

    def manifest(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "chunk_bytes": self.chunk_bytes,
            "total_bytes": int(sum(self.nbytes)),
            "tensors": [
                {"name": n, "shape": list(s), "dtype": d, "nbytes": nb,
                 "n_chunks": nc}
                for n, s, d, nb, nc in zip(
                    self.names, self.shapes, self.dtypes, self.nbytes,
                    self.n_chunks,
                )
            ],
        }

    def chunk_view(self, t: int, c: int) -> memoryview:
        a = self.arrays[t]
        raw = a.reshape(-1).view(np.uint8) if a.nbytes else \
            np.empty(0, np.uint8)
        return memoryview(raw)[c * self.chunk_bytes:(c + 1) * self.chunk_bytes]


class WeightStreamPublisher:
    """Rank-0 host cache + replay server for streamed weight publishes.

    One instance lives for the whole training run; each ``publish()``
    registers a new version. The last ``keep_versions`` publishes stay
    replayable so a server re-admitted by the manager's health loop can
    reconcile to the fleet version without a disk checkpoint existing.
    """

    def __init__(self, experiment: str, trial: str, role: str = "actor",
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 keep_versions: int = 2,
                 chunk_wait_secs: float = 300.0):
        self.chunk_bytes = int(chunk_bytes)
        self.keep_versions = keep_versions
        self.chunk_wait_secs = chunk_wait_secs
        self._cache: Dict[int, _PublishedVersion] = {}
        self._lock = threading.Lock()
        self._closing = False
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self.endpoint = network.advertised_tcp(port)
        self._key = names.weight_stream(experiment, trial, role)
        name_resolve.add(self._key, self.endpoint, replace=True)
        self._serve_thread = threading.Thread(
            target=self._serve_loop, daemon=True, name="weight-stream-serve"
        )
        self._serve_thread.start()
        logger.info(f"weight stream publisher for {role} at {self.endpoint}")

    # ---------------- publishing ----------------

    def publish(self, tensors: Sequence[Tuple[str, Any]], version: int,
                ) -> Dict[str, Any]:
        """Register ``version`` and start gathering its tensors to host in
        the background. ``tensors`` is an ordered [(name, array)] list —
        jax arrays are gathered lazily (pipelined d2h); numpy arrays are
        served as-is. Returns the manifest immediately."""
        pub = _PublishedVersion(version, tensors, self.chunk_bytes)
        with self._lock:
            self._cache[version] = pub
            for v in sorted(self._cache):
                if len(self._cache) <= self.keep_versions:
                    break
                if v != version:
                    del self._cache[v]
        t = threading.Thread(
            target=self._gather_loop, args=(pub,), daemon=True,
            name=f"weight-stream-gather-v{version}",
        )
        t.start()
        return pub.manifest()

    def _gather_loop(self, pub: _PublishedVersion) -> None:
        from areal_tpu.system import memwatch

        t0 = time.monotonic()
        try:
            # The d2h gather holds the compute-dtype publish copy on
            # device until each leaf's ref drops below — the trainer-side
            # HBM high-water mark of a streamed publish.
            with memwatch.watermark("weight_stream/gather"):
                self._gather_leaves(pub)
            pub.gather_secs = time.monotonic() - t0
            pub.complete.set()
            # d2h leg throughput for the unified telemetry stream (the
            # trainer process owns this publisher).
            total = float(sum(pub.nbytes))
            telemetry.set_gauge("weight_stream/gather_secs",
                                pub.gather_secs)
            telemetry.set_gauge(
                "weight_stream/gather_mb_per_sec",
                (total / max(pub.gather_secs, 1e-9)) / (1 << 20),
            )
            telemetry.inc("weight_stream/published_bytes", total)
        except Exception as e:  # noqa: BLE001 — surfaced via chunk errors
            logger.error(f"weight gather v{pub.version} failed: {e}")
            with self._lock:
                self._cache.pop(pub.version, None)
            # Wake blocked chunk waits so they error out instead of hanging.
            for ev in pub.ready:
                ev.set()
            pub.complete.set()

    def _gather_leaves(self, pub: _PublishedVersion) -> None:
        for i, leaf in enumerate(pub.leaves):
            a = _as_wire_array(leaf)
            if a.nbytes != pub.nbytes[i]:
                raise WeightStreamError(
                    f"tensor {pub.names[i]} gathered {a.nbytes} bytes, "
                    f"manifest promised {pub.nbytes[i]}"
                )
            pub.arrays[i] = a
            pub.leaves[i] = None  # drop the device ref
            raw = a.reshape(-1).view(np.uint8) if a.nbytes else \
                np.empty(0, np.uint8)
            cb = pub.chunk_bytes
            pub.crcs[i] = [
                zlib.crc32(memoryview(raw)[c * cb:(c + 1) * cb])
                for c in range(pub.n_chunks[i])
            ]
            pub.ready[i].set()

    def wait_complete(self, version: int, timeout: float = 300.0) -> bool:
        with self._lock:
            pub = self._cache.get(version)
        return pub is not None and pub.complete.wait(timeout)

    # ---------------- serving ----------------

    def _lookup(self, version: int) -> _PublishedVersion:
        with self._lock:
            pub = self._cache.get(version)
        if pub is None:
            raise WeightStreamError(
                f"version {version} not cached "
                f"(have {sorted(self._cache)})"
            )
        return pub

    def _handle(self, frames: List[bytes]) -> List[bytes]:
        """One request → reply frames. Raises :class:`_NotReady` when the
        gather thread has not produced the needed data yet — the serve
        loop defers the request rather than blocking, so one consumer
        racing ahead of the gather never head-of-line-blocks another
        consumer's (already-servable) manifest or chunk requests."""
        cmd = frames[0]
        meta = json.loads(frames[1]) if len(frames) > 1 else {}
        version = int(meta.get("version", -1))
        pub = self._lookup(version)
        if cmd == b"manifest":
            return [b"ok", json.dumps(pub.manifest()).encode()]
        if cmd == b"digest":
            if not pub.complete.is_set():
                raise _NotReady
            self._lookup(version)  # gather failure evicts the cache entry
            return [b"ok", json.dumps(
                {"version": version, "crcs": pub.crcs}
            ).encode()]
        if cmd == b"chunk":
            t, c = int(meta["tensor"]), int(meta["chunk"])
            if not (0 <= t < len(pub.names)) or not (0 <= c < pub.n_chunks[t]):
                raise WeightStreamError(f"chunk ({t},{c}) out of range")
            if not pub.ready[t].is_set():
                raise _NotReady
            if pub.arrays[t] is None:  # gather failed
                raise WeightStreamError("publisher gather failed")
            telemetry.inc("weight_stream/chunks_served")
            return [
                b"ok",
                json.dumps({"version": version, "tensor": t, "chunk": c,
                            "crc32": pub.crcs[t][c]}).encode(),
                pub.chunk_view(t, c),
            ]
        raise WeightStreamError(f"unknown command {cmd!r}")

    def _reply(self, ident: bytes, reply: List[bytes]) -> None:
        try:
            self._sock.send_multipart([ident, *reply], copy=False)
        except zmq.ZMQError:
            # Consumer died mid-stream: ROUTER drops the reply; the
            # manager's eviction/retry machinery owns that server now.
            pass

    def _try_serve(self, ident: bytes, frames: List[bytes]) -> bool:
        """Handle one request; returns False iff it must be deferred."""
        try:
            reply = self._handle(frames)
        except _NotReady:
            return False
        except WeightStreamError as e:
            reply = [b"err", str(e).encode()]
        except Exception as e:  # noqa: BLE001 — keep serving
            logger.error(f"weight stream request failed: {e}")
            reply = [b"err", str(e).encode()]
        self._reply(ident, reply)
        return True

    def _serve_loop(self) -> None:
        # Requests waiting on the gather thread: [(ident, frames, deadline)].
        pending: List[tuple] = []
        while not self._closing:
            if self._sock.poll(20 if pending else 100):
                while True:
                    try:
                        ident, *frames = self._sock.recv_multipart(
                            zmq.NOBLOCK
                        )
                    except zmq.Again:
                        break
                    if not self._try_serve(ident, frames):
                        pending.append((
                            ident, frames,
                            time.monotonic() + self.chunk_wait_secs,
                        ))
            still = []
            for ident, frames, deadline in pending:
                if self._try_serve(ident, frames):
                    continue
                if time.monotonic() > deadline:
                    self._reply(ident, [
                        b"err",
                        b"timed out waiting for the gather thread",
                    ])
                    continue
                still.append((ident, frames, deadline))
            pending = still

    def close(self) -> None:
        self._closing = True
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        self._serve_thread.join(timeout=2)
        self._sock.close(linger=0)


class WeightStreamConsumer:
    """One server's view of a publisher: fetch manifest, stream tensors
    with a bounded request window, verify the digest."""

    def __init__(self, endpoint: str,
                 pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
                 timeout_secs: float = 600.0):
        # timeout_secs must cover the publisher-side d2h gather of the
        # LARGEST tensor (a chunk request blocks server-side until its
        # tensor is gathered — minutes for a ~300 MB embedding on a slow
        # tunnel), not just wire latency; it is a liveness backstop, not a
        # performance bound.
        self.endpoint = endpoint
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.timeout_secs = timeout_secs
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.connect(endpoint)
        # Stats for the bench / metrics: where the wall-clock went.
        self.bytes_received = 0
        self.checksum_secs = 0.0  # host-side CPU work (the "io" analogue)
        self.wire_wait_secs = 0.0

    def _request(self, cmd: bytes, meta: Dict[str, Any]) -> None:
        self._sock.send_multipart([cmd, json.dumps(meta).encode()])

    def _recv(self) -> List[bytes]:
        t0 = time.monotonic()
        if not self._sock.poll(int(self.timeout_secs * 1000)):
            raise WeightStreamError(
                f"no reply from {self.endpoint} within {self.timeout_secs}s"
            )
        frames = self._sock.recv_multipart()
        self.wire_wait_secs += time.monotonic() - t0
        if frames[0] == b"err":
            raise WeightStreamError(
                f"publisher error: {frames[1].decode(errors='replace')}"
            )
        if frames[0] != b"ok":
            raise WeightStreamError(f"bad reply frame {frames[0]!r}")
        return frames[1:]

    def fetch_manifest(self, version: int) -> Dict[str, Any]:
        self._request(b"manifest", {"version": version})
        manifest = json.loads(self._recv()[0])
        if int(manifest["version"]) != version:
            raise WeightStreamError(
                f"manifest version {manifest['version']} != requested "
                f"{version}"
            )
        return manifest

    def iter_tensors(
        self, version: int, manifest: Dict[str, Any]
    ) -> Iterator[Tuple[str, np.ndarray]]:
        """Yield (name, array) in manifest order, keeping up to
        ``pipeline_depth`` chunk requests in flight so the wire leg overlaps
        whatever the caller does with each yielded tensor (device_put).
        Records per-chunk CRC32s for :meth:`verify_digest`."""
        coords = [
            (t, c)
            for t, spec in enumerate(manifest["tensors"])
            for c in range(spec["n_chunks"])
        ]
        self._local_crcs: List[List[int]] = [
            [0] * spec["n_chunks"] for spec in manifest["tensors"]
        ]
        pending = 0
        sent = 0
        parts: List[bytes] = []
        cur_tensor = 0
        for t, c in coords[: self.pipeline_depth]:
            self._request(b"chunk", {"version": version, "tensor": t,
                                     "chunk": c})
            sent += 1
            pending += 1
        for t, c in coords:
            meta_raw, payload = self._recv()
            pending -= 1
            if sent < len(coords):
                nt, nc = coords[sent]
                self._request(b"chunk", {"version": version, "tensor": nt,
                                         "chunk": nc})
                sent += 1
                pending += 1
            meta = json.loads(meta_raw)
            if (int(meta["version"]), int(meta["tensor"]),
                    int(meta["chunk"])) != (version, t, c):
                raise WeightStreamError(
                    f"out-of-order chunk: expected v{version} ({t},{c}), "
                    f"got v{meta['version']} "
                    f"({meta['tensor']},{meta['chunk']})"
                )
            t0 = time.monotonic()
            crc = zlib.crc32(payload)
            if crc != int(meta["crc32"]):
                raise WeightStreamError(
                    f"chunk ({t},{c}) checksum mismatch: wire {crc} != "
                    f"published {meta['crc32']}"
                )
            self._local_crcs[t][c] = crc
            self.bytes_received += len(payload)
            parts.append(payload)
            self.checksum_secs += time.monotonic() - t0
            spec = manifest["tensors"][t]
            if c == spec["n_chunks"] - 1:
                t0 = time.monotonic()
                buf = parts[0] if len(parts) == 1 else b"".join(parts)
                if len(buf) != spec["nbytes"]:
                    raise WeightStreamError(
                        f"tensor {spec['name']}: received {len(buf)} bytes, "
                        f"manifest promised {spec['nbytes']}"
                    )
                arr = np.frombuffer(buf, dtype=_np_dtype(spec["dtype"]))
                arr = arr.reshape(spec["shape"])
                parts = []
                cur_tensor += 1
                self.checksum_secs += time.monotonic() - t0
                yield spec["name"], arr
        assert pending == 0 and cur_tensor == len(manifest["tensors"])

    def verify_digest(self, version: int) -> None:
        """Compare locally computed per-chunk CRCs against the publisher's
        complete digest. Raises if ANY chunk differs — the caller must not
        swap weights before this passes."""
        self._request(b"digest", {"version": version})
        digest = json.loads(self._recv()[0])
        t0 = time.monotonic()
        if digest["crcs"] != self._local_crcs:
            raise WeightStreamError(
                f"digest mismatch for v{version}: stream was torn or "
                "reordered; aborting swap"
            )
        self.checksum_secs += time.monotonic() - t0

    def fetch(self, version: int) -> Tuple[Dict[str, Any],
                                           Dict[str, np.ndarray]]:
        """Convenience: full verified transfer → (manifest, {name: array})."""
        manifest = self.fetch_manifest(version)
        out = dict(self.iter_tensors(version, manifest))
        self.verify_digest(version)
        return manifest, out

    def close(self) -> None:
        self._sock.close(linger=0)
