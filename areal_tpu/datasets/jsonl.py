"""Datasets: jsonl prompt/SFT/paired-reward/math-code loaders.

Parity targets (``realhf/impl/dataset/``): ``PromptDataset``
(prompt_dataset.py:16), ``PromptAnswerDataset`` (SFT), ``RewardModeling-
PairedDataset``, ``MATHCodePromptDataset`` (math_code_dataset.py:90, with
dynamic difficulty filtering), and the shared loader
``load_shuffle_split_dataset`` (realhf/api/core/data_api.py:754 — every DP
rank deterministically owns a disjoint shard by seed).

No torch dependency: a dataset here is a plain object with ``__len__`` /
``__getitem__`` returning ``SequenceSample``s (host numpy), plus an optional
``filter(eval_scores)`` hook. Tokenizers are anything with
``encode(str) -> List[int]`` (HF tokenizers qualify).
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Any, Callable, Dict, Hashable, List, Optional

import numpy as np

from areal_tpu.api.data import SequenceSample
from areal_tpu.api.model import register_dataset
from areal_tpu.base import logging

logger = logging.getLogger("datasets")

RL_TASKS = ("math", "code", "rlhf", "stem")


def _encode(tokenizer, text: str) -> List[int]:
    ids = tokenizer.encode(text)
    if hasattr(ids, "ids"):  # tokenizers.Encoding
        ids = ids.ids
    return list(ids)


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    # "@" is reserved as the structural separator in sample ids
    # ("<query_id>@<group_idx>", "<query_id>@r<epoch>"); a raw query_id
    # containing it would make reward lookups silently miss. Fail loudly.
    for r in records:
        if "@" in str(r.get("query_id", "")):
            raise ValueError(
                f"query_id {r['query_id']!r} in {path} contains '@', which "
                "is reserved for sample-id suffixes; rename the record"
            )
    return records


def load_shuffle_split(
    data: List[Dict],
    seed: int,
    dp_rank: int,
    dp_size: int,
) -> List[Dict]:
    """Deterministic disjoint shard per DP rank (reference data_api.py:754):
    one global shuffle by seed, then a contiguous slice per rank."""
    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(data))
    bounds = np.linspace(0, len(data), dp_size + 1).astype(int)
    idx = perm[bounds[dp_rank] : bounds[dp_rank + 1]]
    return [data[i] for i in idx]


class JsonlDatasetBase:
    """Common machinery: load → validate → shard → tokenize lazily."""

    def __init__(
        self,
        dataset_path: Optional[str] = None,
        dataset_builder: Optional[Callable[[], List[Dict]]] = None,
        tokenizer=None,
        seed: int = 1,
        dp_rank: int = 0,
        dp_size: int = 1,
        max_length: Optional[int] = None,
    ):
        raw = load_jsonl(dataset_path) if dataset_path else dataset_builder()
        raw = [d for d in raw if self._validate(d)]
        self.records = load_shuffle_split(raw, seed, dp_rank, dp_size)
        self.tokenizer = tokenizer
        self.max_length = max_length
        self.seed = seed

    def _validate(self, d: Dict) -> bool:
        return True

    def __len__(self) -> int:
        return len(self.records)

    def filter(self, eval_scores: Dict[Hashable, float]) -> None:
        """Dynamic difficulty filtering hook (no-op by default)."""

    def _truncate(self, ids: List[int]) -> List[int]:
        if self.max_length is not None and len(ids) > self.max_length:
            return ids[: self.max_length]
        return ids


class PromptDataset(JsonlDatasetBase):
    """Records: {"prompt": str, "query_id": str} → SequenceSample with
    ``packed_prompts`` (reference prompt_dataset.py:16)."""

    def _validate(self, d):
        return isinstance(d.get("prompt"), str)

    def __getitem__(self, i: int) -> SequenceSample:
        d = self.records[i]
        ids = self._truncate(_encode(self.tokenizer, d["prompt"]))
        return SequenceSample.from_default(
            ids=[str(d.get("query_id", i))],
            data={"packed_prompts": np.asarray(ids, np.int32)},
            seqlens=[len(ids)],
            metadata={"task": [d.get("task", "math")]},
        )


class PromptAnswerDataset(JsonlDatasetBase):
    """SFT records: {"prompt": str, "answer": str} → packed_input_ids +
    prompt_mask (True on prompt tokens, excluded from the loss;
    reference prompt_answer_dataset.py)."""

    def _validate(self, d):
        return isinstance(d.get("prompt"), str) and isinstance(d.get("answer"), str)

    def __getitem__(self, i: int) -> SequenceSample:
        d = self.records[i]
        p = _encode(self.tokenizer, d["prompt"])
        a = _encode(self.tokenizer, d["prompt"] + d["answer"])[len(p):]
        if not a:  # degenerate tokenization; fall back to direct encoding
            a = _encode(self.tokenizer, d["answer"])
        ids = self._truncate(p + a)
        mask = ([1] * len(p) + [0] * len(a))[: len(ids)]
        return SequenceSample.from_default(
            ids=[str(d.get("query_id", i))],
            data={
                "packed_input_ids": np.asarray(ids, np.int32),
                "prompt_mask": np.asarray(mask, np.int32),
            },
            seqlens=[len(ids)],
        )


class RewardModelingPairedDataset(JsonlDatasetBase):
    """Records: {"prompt", "pos_answers": [...], "neg_answers": [...]} →
    packed_input_ids holding pos/neg pairs interleaved, group_factor
    metadata (reference rw_paired_dataset.py)."""

    def _validate(self, d):
        return (
            isinstance(d.get("prompt"), str)
            and d.get("pos_answers")
            and d.get("neg_answers")
            and len(d["pos_answers"]) == len(d["neg_answers"])
        )

    def __getitem__(self, i: int) -> SequenceSample:
        d = self.records[i]
        p = _encode(self.tokenizer, d["prompt"])
        seqs: List[List[int]] = []
        for pos, neg in zip(d["pos_answers"], d["neg_answers"]):
            for ans in (pos, neg):
                seqs.append(self._truncate(p + _encode(self.tokenizer, ans)))
        flat = np.asarray([t for s in seqs for t in s], np.int32)
        n_pairs = len(d["pos_answers"])
        return SequenceSample(
            ids=[str(d.get("query_id", i))],
            keys={"packed_input_ids"},
            seqlens={"packed_input_ids": [[len(s) for s in seqs]]},
            data={"packed_input_ids": flat},
            metadata={"n_pairs": [n_pairs]},
        )


class MathCodePromptDataset(PromptDataset):
    """RL prompt dataset with per-task metadata and dynamic difficulty
    filtering (reference math_code_dataset.py:90,175).

    Records: {"query_id", "prompt", "task": "math"|"code",
    "solutions": [str]} and, for code, {"input_output": json-str}.
    ``filter``: drop prompts whose running mean eval score exceeds
    ``filter_threshold`` (too easy), up to ``max_filter_percentage`` per call.
    """

    def __init__(
        self,
        *args,
        filter_threshold: float = 1e4,
        max_filter_percentage: float = 0.0,
        **kw,
    ):
        super().__init__(*args, **kw)
        self.filter_threshold = filter_threshold
        self.max_filter_percentage = max_filter_percentage
        self.id2info = {str(d["query_id"]): d for d in self.records}

    def _validate(self, d):
        if not isinstance(d.get("prompt"), str) or "query_id" not in d:
            return False
        task = d.setdefault("task", "math")
        if task in ("math", "stem"):
            ok = isinstance(d.get("solutions"), list) and all(
                isinstance(s, str) for s in d["solutions"]
            )
        elif task == "code":
            try:
                io = json.loads(d.get("input_output", "null")) or {}
                ok = len(io.get("inputs", [])) == len(io.get("outputs", []))
            except json.JSONDecodeError:
                ok = False
        else:
            ok = False
        if not ok:
            logger.warning(f"invalid record {d.get('query_id')}; omitted")
        return ok

    def __getitem__(self, i: int) -> SequenceSample:
        d = self.records[i]
        ids = self._truncate(_encode(self.tokenizer, d["prompt"]))
        return SequenceSample.from_default(
            ids=[str(d["query_id"])],
            data={
                "packed_prompts": np.asarray(ids, np.int32),
                "task_ids": np.asarray([RL_TASKS.index(d["task"])], np.int32),
            },
            seqlens=[len(ids)],
            metadata={"task": [d["task"]]},
        )

    def filter(self, eval_scores: Dict[Hashable, float]) -> None:
        scores = defaultdict(list)
        for qid, s in eval_scores.items():
            scores[str(qid)].append(float(s))
        means = {q: np.mean(v) for q, v in scores.items()}
        candidates = [
            i
            for i, d in enumerate(self.records)
            if means.get(str(d["query_id"]), -np.inf) > self.filter_threshold
        ]
        cap = int(self.max_filter_percentage * len(self.records))
        drop = set(candidates[:cap])
        if drop:
            logger.info(f"difficulty filter: dropping {len(drop)} records")
            self.records = [d for i, d in enumerate(self.records) if i not in drop]
            self.id2info = {str(d["query_id"]): d for d in self.records}


register_dataset("prompt", PromptDataset)
register_dataset("prompt_answer", PromptAnswerDataset)
register_dataset("rw_paired", RewardModelingPairedDataset)
register_dataset("math_code_prompt", MathCodePromptDataset)
