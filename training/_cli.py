"""Shared CLI plumbing for the training entry scripts.

Replaces the reference's hydra stack (``training/main_async_ppo.py:15-25``)
with the in-repo YAML + dotted-override merge: the command surface is the
same (``key=value`` overrides, e.g. ``examples/run_async_ppo.sh`` ports
verbatim), plus ``--config <yaml>`` and ``--backend=tpu``.
"""

from __future__ import annotations

import sys
from typing import List, Tuple


def parse_argv(argv: List[str]) -> Tuple[dict, List[str]]:
    """Split flags (--config/--backend/--help) from key=value overrides."""
    flags = {"config": None, "backend": "tpu", "help": False}
    overrides: List[str] = []
    it = iter(argv)
    for a in it:
        if a == "--help" or a == "-h":
            flags["help"] = True
        elif a.startswith("--backend="):
            flags["backend"] = a.split("=", 1)[1]
        elif a == "--backend":
            flags["backend"] = next(it)
        elif a.startswith("--config="):
            flags["config"] = a.split("=", 1)[1]
        elif a == "--config":
            flags["config"] = next(it)
        elif "=" in a and not a.startswith("-"):
            overrides.append(a)
        else:
            raise SystemExit(f"unrecognized argument: {a!r}")
    return flags, overrides


def main(experiment_name: str, default_cls) -> None:
    from areal_tpu.api import cli_args as CA

    flags, overrides = parse_argv(sys.argv[1:])
    cfg = default_cls()
    if flags["help"]:
        CA.print_config_help(cfg)
        raise SystemExit(0)
    if flags["backend"] not in ("tpu", "jax"):
        raise SystemExit(
            f"--backend={flags['backend']} is not supported by the TPU "
            "framework (use --backend=tpu)"
        )
    if flags["config"]:
        CA.load_yaml(cfg, flags["config"])
    CA.apply_overrides(cfg, overrides)
    # Fail bad modes (e.g. the descoped mode=ray) at parse time, while
    # the operator is still at the command line.
    CA.validate_config(cfg)
    cfg.resolve_trial_name()

    from areal_tpu.base import logging

    logger = logging.getLogger("quickstart")
    logger.info(
        f"launching {experiment_name}: experiment_name={cfg.experiment_name} "
        f"trial_name={cfg.trial_name} allocation_mode={cfg.allocation_mode!r}"
    )

    from areal_tpu.apps.launcher import run_experiment

    result = run_experiment(cfg)
    logger.info(f"experiment finished: steps={result.get('steps')}")
