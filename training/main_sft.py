"""SFT entry point (reference ``training/main_sft.py``).

    python training/main_sft.py --backend=tpu \
        model.path=/ckpts/Qwen3-1.7B dataset.path=sft.jsonl
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from areal_tpu.experiments.sft_exp import SFTConfig  # noqa: E402
from training._cli import main  # noqa: E402

if __name__ == "__main__":
    main("sft", SFTConfig)
