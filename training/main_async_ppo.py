"""Async-PPO entry point (reference ``training/main_async_ppo.py``).

    python training/main_async_ppo.py --backend=tpu \
        actor.path=/ckpts/Qwen3-1.7B dataset.path=data.jsonl \
        allocation_mode=gen.d4+d2f2t2 dataset.train_bs_n_seqs=32 \
        group_size=8 max_head_offpolicyness=4 max_concurrent_rollouts=16
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from areal_tpu.experiments.async_ppo_math_exp import AsyncPPOMATHConfig  # noqa: E402
from training._cli import main  # noqa: E402

if __name__ == "__main__":
    main("async-ppo-math", AsyncPPOMATHConfig)
