"""Sync-PPO entry point (reference ``training/main_sync_ppo.py``).

    python training/main_sync_ppo.py --backend=tpu \
        actor.path=/ckpts/Qwen3-1.7B dataset.path=data.jsonl \
        allocation_mode=d2f2t2 dataset.train_bs_n_seqs=32 group_size=8
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig  # noqa: E402
from training._cli import main  # noqa: E402

if __name__ == "__main__":
    main("ppo-math", PPOMATHConfig)
